"""Deployment-only inference API.

Parity: reference c_predict_api (src/c_api/c_predict_api.cc:44-265 —
MXPredCreate / MXPredCreatePartialOut / MXPredSetInput / MXPredForward /
MXPredPartialForward / MXPredGetOutput / MXPredReshape): load
(symbol JSON + param bytes), bind a forward-only executor, run.  No
optimizer / kvstore / module machinery is touched — this is the path an
inference service embeds.

Partial forward ≙ `output_names` / `output_layer`: the reference steps the
graph node-by-node on the engine; under the one-XLA-executable design the
equivalent is selecting internal entries as extra outputs (feature
extraction), which compiles a prefix executable.
"""
from __future__ import annotations

import numpy as _np

from . import ndarray as nd
from . import symbol as sym
from .base import MXNetError
from .context import cpu, current_context
from . import locks

__all__ = ["Predictor"]

# bound on the per-Predictor executor-signature cache: covers every
# realistic serving bucket ladder (≤ ~10 signatures) and reshape
# ping-pong with room to spare, while a pathological caller reshaping
# to per-request-unique shapes evicts oldest-first instead of retaining
# one bound executor (device buffers + jitted programs) per shape
# forever (the lazy.py _FUSION_CACHE_CAP discipline)
_EXEC_CACHE_CAP = 32


class Predictor:
    """One bound inference session (reference PredictorHandle)."""

    def __init__(self, symbol_json, param_bytes, input_shapes, ctx=None,
                 output_names=None, type_dict=None, dtype_mode=None,
                 calib_table=None):
        """symbol_json: JSON string (or dict of a loaded graph);
        param_bytes: raw .params file content (reference binary NDArray-list
        ABI or the native container); input_shapes: {name: shape}.

        `dtype_mode` selects the serving numerics per PREDICTOR (and so
        per serving tenant — docs/serving.md "Int8 serving"):

          * ``None`` / ``"f32"`` — the legacy full-precision bind;
          * ``"bf16"`` — mixed-precision executors (params stored f32,
            conv/matmul compute in bf16 via ``compute_dtype``);
          * ``"int8"`` — the post-training-quantized graph: eligible
            conv/FC nodes rewritten onto the int8 kernels using the
            required `calib_table` (a :class:`mxnet_tpu.quant.CalibTable`,
            its dict form, or a path to a saved one), everything else in
            bf16.  Params load UNCHANGED — the calibrated ``*_act_amax``
            scale vectors ride as extra fp32 arguments.

        The mode is part of the executor-signature cache key, so one
        process serving the same graph under several modes compiles
        each (mode, shape) pair exactly once."""
        self._ctx = ctx or current_context()
        if dtype_mode not in (None, "f32", "bf16", "int8"):
            raise MXNetError(
                "dtype_mode must be one of None/'f32'/'bf16'/'int8', got "
                "%r" % (dtype_mode,))
        self._dtype_mode = dtype_mode or "f32"
        self._fp32_names = ()
        net = sym.load_json(symbol_json) if isinstance(symbol_json, str) else symbol_json
        if output_names:
            internals = net.get_internals()
            avail = internals.list_outputs()
            picked = []
            for name in output_names:
                if name not in avail:
                    raise MXNetError("output %r not found; internals: %s..."
                                     % (name, avail[:20]))
                picked.append(internals[name])
            net = sym.Group(picked) if len(picked) > 1 else picked[0]
        self._symbol = net
        save_dict = nd.loads(param_bytes) if isinstance(param_bytes, bytes) \
            else dict(param_bytes)
        self._arg_params, self._aux_params = {}, {}
        for k, v in save_dict.items():
            if k.startswith("arg:"):
                self._arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                self._aux_params[k[4:]] = v
            else:  # plain names accepted too
                self._arg_params[k] = v
        if self._dtype_mode == "int8":
            if calib_table is None:
                raise MXNetError(
                    "dtype_mode='int8' needs a calib_table (run "
                    "mx.quant.calibrate over representative batches "
                    "first; docs/serving.md 'Int8 serving')")
            from .quant import CalibTable, quantize_symbol

            if isinstance(calib_table, str):
                calib_table = CalibTable.load(calib_table)
            self._symbol, scale_args = quantize_symbol(self._symbol,
                                                       calib_table)
            self._arg_params.update(scale_args)
            # calibrated ranges stay fp32 under the bf16 compute cast:
            # the quantize step divides by them, and re-rounding the
            # scale itself through bf16 shifts every grid point
            self._fp32_names = tuple(scale_args)
        # executors cached by input-shape signature: reshape() and the
        # serving bucket ladder (serving/session.py) re-bind the SAME
        # graph at many batch sizes, and each signature's executor (and
        # its compiled programs) must be built once, not per visit
        self._exec_cache = {}
        # executor_for may be called from several serving threads (a
        # warmup racing the batcher): the check-then-build-then-evict
        # sequence must be atomic or the same signature binds twice
        import threading

        self._cache_lock = locks.lock("predict.cache")
        self._type_dict = dict(type_dict) if type_dict else None
        self._bind(dict(input_shapes))

    @classmethod
    def from_checkpoint(cls, prefix, epoch, input_shapes, ctx=None,
                        output_names=None):
        """Convenience: load prefix-symbol.json + prefix-%04d.params."""
        with open("%s-symbol.json" % prefix) as f:
            json_str = f.read()
        with open("%s-%04d.params" % (prefix, epoch), "rb") as f:
            params = f.read()
        return cls(json_str, params, input_shapes, ctx=ctx,
                   output_names=output_names)

    def _bind(self, input_shapes):
        self._input_names = list(input_shapes)
        self._exec = self.executor_for(input_shapes)

    def executor_for(self, input_shapes):
        """Bound forward-only executor for these input shapes, from the
        signature cache: the first visit of a signature binds (and its
        first forward compiles); every later visit — another reshape()
        round trip, another fill of the same serving bucket — returns
        the SAME executor, so its jit cache keeps the compiled program.
        Counted in predict.bind_cache_hits/_misses."""
        self._check_open()
        # the dtype mode leads the signature.  Today it is constant per
        # Predictor (the cache is instance-scoped and the mode fixed at
        # construction — mixed serving tenants are separate Predictors
        # with separate caches), so this key component is an INVARIANT
        # STATEMENT, not a live discriminator: it makes the
        # (mode, shapes) -> program contract explicit and keeps any
        # future mode-switching surface from silently aliasing programs
        # across numerics
        sig = (self._dtype_mode,) + tuple(
            sorted((n, tuple(s)) for n, s in input_shapes.items()))
        from . import telemetry

        with self._cache_lock:
            # re-check under the lock: a concurrent close() tears down
            # under this lock, so passing here guarantees a live cache
            self._check_open()
            exe = self._exec_cache.get(sig)
            if telemetry.enabled():
                telemetry.inc("predict.bind_cache_hits" if exe is not None
                              else "predict.bind_cache_misses")
            if exe is None:
                while len(self._exec_cache) >= _EXEC_CACHE_CAP:
                    old = self._exec_cache.pop(next(iter(self._exec_cache)))
                    # eviction is a memory event, not just a cache
                    # event: the evicted executor's compiled programs
                    # leave the ProgramFootprint table (obs/memory.py)
                    # and mem.programs_evicted ticks, so the program
                    # census cannot drift upward across a long-lived
                    # serving process
                    old.release_footprints(evicted=True)
                exe = self._exec_cache[sig] = \
                    self._build_exec(dict(input_shapes))
        return exe

    def _build_exec(self, input_shapes):
        type_dict = self._type_dict
        arg_names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**input_shapes)
        if arg_shapes is None:
            raise MXNetError("cannot infer shapes from %s" % input_shapes)
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in input_shapes:
                args[name] = nd.zeros(shape, ctx=self._ctx)
            elif name in self._arg_params:
                p = self._arg_params[name]
                if tuple(p.shape) != tuple(shape):
                    raise MXNetError("param %s shape %s != expected %s"
                                     % (name, p.shape, shape))
                args[name] = p
            elif name.endswith("label"):
                # labels are dead inputs at inference; zero-fill (the
                # reference predictor does the same for aux label args)
                args[name] = nd.zeros(shape, ctx=self._ctx)
            else:
                raise MXNetError("missing parameter %s" % name)
        aux = {}
        for name, shape in zip(aux_names, aux_shapes):
            if name not in self._aux_params:
                raise MXNetError("missing aux state %s" % name)
            aux[name] = self._aux_params[name]
        if self._dtype_mode in ("bf16", "int8"):
            from .executor import Executor

            return Executor.bind(self._symbol, self._ctx, args,
                                 args_grad=None, grad_req="null",
                                 aux_states=aux, compute_dtype="bfloat16",
                                 fp32_names=self._fp32_names)
        return self._symbol.bind(self._ctx, args, args_grad=None,
                                 grad_req="null", aux_states=aux)

    @property
    def dtype_mode(self):
        """The serving numerics this predictor binds ('f32'/'bf16'/
        'int8') — fixed at construction; a tenant that should serve
        another mode is a NEW Predictor over the same symbol+params."""
        return self._dtype_mode

    def footprint_bytes(self):
        """Predicted resident bytes of this predictor's parameters
        (arg + aux) — the byte-budget admission input
        (obs/memory.py admit; docs/observability.md "Memory
        observability").  Analytic from shapes/dtypes: callable before
        any program has compiled."""
        from .obs import memory

        total = 0
        for d in (self._arg_params, self._aux_params):
            for v in (d or {}).values():
                total += memory.nbytes_of(v)
        return total

    def _check_open(self):
        if self._exec_cache is None:
            raise MXNetError("Predictor is closed (close() released its "
                             "executors and parameters; build a new one)")

    def close(self):
        """Release the bound executors (their jitted programs and device
        input/output buffers) and drop the parameter references, so a
        long-lived serving process can retire a model without waiting
        for GC.  Idempotent; every later API call raises a clear error
        (reference MXPredFree, c_predict_api.cc:237).  Teardown happens
        under the cache lock, so a caller racing close() gets the
        closed-error, never a half-torn-down predictor."""
        with self._cache_lock:
            cached = (self._exec_cache or {}).values()
            for exe in cached:
                exe.release_footprints()
            self._exec = None
            self._exec_cache = None
            self._arg_params = {}
            self._aux_params = {}

    # -- the C predict API surface --------------------------------------
    def set_input(self, name, data):
        """MXPredSetInput (c_predict_api.cc:243).  A flat buffer with the
        right element count is accepted and reshaped (the C ABI passes
        row-major float pointers without shape)."""
        self._check_open()
        if name not in self._input_names:
            raise MXNetError("unknown input %s (inputs: %s)"
                             % (name, self._input_names))
        target = self._exec.arg_dict[name]
        data = _np.asarray(data, dtype=_np.float32)
        if data.ndim == 1 and data.size == target.size:
            data = data.reshape(target.shape)
        target[:] = data

    def forward(self, **inputs):
        """MXPredForward (c_predict_api.cc:258); inputs may be given inline."""
        self._check_open()
        for name, data in inputs.items():
            self.set_input(name, data)
        self._exec.forward(is_train=False)
        return self

    def get_output(self, index=0):
        """MXPredGetOutput → numpy."""
        self._check_open()
        return self._exec.outputs[index].asnumpy()

    def get_output_shape(self, index=0):
        """MXPredGetOutputShape: shape tuple of output `index`."""
        self._check_open()
        return tuple(int(d) for d in self._exec.outputs[index].shape)

    def get_output_bytes(self, index=0):
        """Row-major float32 bytes of output `index` (the C ABI's
        MXPredGetOutput copies these into caller memory)."""
        out = _np.ascontiguousarray(self.get_output(index), dtype=_np.float32)
        return out.tobytes()

    @property
    def num_outputs(self):
        self._check_open()
        return len(self._exec.outputs)

    def reshape(self, input_shapes):
        """MXPredReshape (c_predict_api.cc:150-210): rebind with new input
        shapes, parameters shared.  A signature seen before comes out of
        the executor cache, so a reshape ping-pong (bucketed inference)
        never recompiles."""
        self._bind(dict(input_shapes))
        return self
