"""Generic class-registry factories.

Parity: reference python/mxnet/registry.py (get_register_func /
get_alias_func / get_create_func) — the factory triple behind
`Optimizer.register` / `Initializer.register` / `mx.optimizer.create`
style plugin points.  Keyed per base class; names are case-insensitive;
`create` accepts an instance (passed through), a name, a name+kwargs JSON
list, or a kwargs JSON dict."""
from __future__ import annotations

import json
import warnings

__all__ = ["get_register_func", "get_alias_func", "get_create_func"]

_REGISTRY = {}


def get_register_func(base_class, nickname):
    """Return a `register(klass, name=None)` function for `base_class`."""
    registry = _REGISTRY.setdefault(base_class, {})

    def register(klass, name=None):
        assert issubclass(klass, base_class), \
            "Can only register subclass of %s" % base_class.__name__
        if name is None:
            name = klass.__name__
        name = name.lower()
        if name in registry:
            warnings.warn(
                "New %s %s.%s registered with name %s is overriding "
                "existing %s %s.%s" % (
                    nickname, klass.__module__, klass.__name__, name,
                    nickname, registry[name].__module__,
                    registry[name].__name__),
                UserWarning, stacklevel=2)
        registry[name] = klass
        return klass

    register.__doc__ = "Register %s to the %s factory" % (nickname, nickname)
    return register


def get_alias_func(base_class, nickname):
    """Return an `@alias("a", "b")` decorator registering under each name."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass
        return reg
    return alias


def get_create_func(base_class, nickname):
    """Return a `create(name_or_instance, **kwargs)` factory."""
    registry = _REGISTRY.setdefault(base_class, {})

    def create(*args, **kwargs):
        if len(args):
            name = args[0]
            args = args[1:]
        else:
            name = kwargs.pop(nickname)
        if isinstance(name, base_class):
            assert not args and not kwargs, (
                "%s is already an instance. Additional arguments are "
                "invalid" % nickname)
            return name
        if isinstance(name, dict):
            return create(**name)
        assert isinstance(name, str), "%s must be of string type" % nickname
        if name.startswith("["):
            assert not args and not kwargs
            name, kwargs = json.loads(name)
            return create(name, **kwargs)
        if name.startswith("{"):
            assert not args and not kwargs
            kwargs = json.loads(name)
            return create(**kwargs)
        name = name.lower()
        assert name in registry, \
            "%s is not registered. Please register with %s.register first" % (
                name, nickname)
        return registry[name](*args, **kwargs)

    create.__doc__ = (
        "Create a %s instance from config (name, instance, or JSON)."
        % nickname)
    return create
