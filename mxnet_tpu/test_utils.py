"""Testing fixtures (parity: reference python/mxnet/test_utils.py).

The reference's highest-leverage correctness harness (SURVEY.md §4):
`check_numeric_gradient` (finite differences, test_utils.py:420),
`check_symbolic_forward/backward` (:533,:598), `check_consistency` (:765 —
same graph on several contexts/dtypes cross-compared).
"""
from __future__ import annotations

import numpy as _np

from . import ndarray as nd
from . import symbol as sym
from .context import Context, cpu, current_context
from .ndarray import NDArray, array

__all__ = [
    "default_context", "set_default_context", "assert_almost_equal", "same", "reldiff",
    "almost_equal", "rand_ndarray", "rand_shape_2d", "rand_shape_3d", "random_arrays",
    "check_numeric_gradient", "check_symbolic_forward", "check_symbolic_backward",
    "check_consistency", "simple_forward",
]

_DEFAULT_CTX = None


def default_context():
    """Context for the test suite (parity: test_utils.py default_context:28)."""
    global _DEFAULT_CTX
    if _DEFAULT_CTX is None:
        return current_context()
    return _DEFAULT_CTX


def set_default_context(ctx):
    global _DEFAULT_CTX
    _DEFAULT_CTX = ctx


def same(a, b):
    return _np.array_equal(a, b)


def reldiff(a, b):
    diff = _np.sum(_np.abs(a - b))
    norm = _np.sum(_np.abs(a)) + _np.sum(_np.abs(b))
    if diff == 0:
        return 0
    return diff / norm


def almost_equal(a, b, rtol=None, atol=None):
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    return _np.allclose(a, b, rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    """Assert allclose with readable error (parity: test_utils.py:129)."""
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    a = a.asnumpy() if isinstance(a, NDArray) else _np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else _np.asarray(b)
    if almost_equal(a, b, rtol, atol):
        return
    index = _np.unravel_index(_np.argmax(_np.abs(a - b)), a.shape) if a.shape else ()
    rel = reldiff(a, b)
    raise AssertionError(
        "Error %f exceeds tolerance rtol=%f, atol=%f. Location of maximum error: %s, %s=%s, %s=%s"
        % (rel, rtol, atol, str(index),
           names[0], a[index] if a.shape else a, names[1], b[index] if b.shape else b)
    )


def rand_shape_2d(dim0=10, dim1=10):
    return (_np.random.randint(1, dim0 + 1), _np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_np.random.randint(1, dim0 + 1), _np.random.randint(1, dim1 + 1),
            _np.random.randint(1, dim2 + 1))


def rand_ndarray(shape, ctx=None, dtype="float32"):
    return array(_np.random.uniform(-1.0, 1.0, shape).astype(dtype), ctx=ctx)


def random_arrays(*shapes):
    arrays = [_np.random.randn(*s).astype("float32") for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def simple_forward(sym_, ctx=None, is_train=False, **inputs):
    """Forward a symbol with numpy inputs, return numpy outputs
    (parity: test_utils.py simple_forward)."""
    ctx = ctx or default_context()
    inputs = {k: array(v, ctx=ctx) for k, v in inputs.items()}
    exe = sym_.bind(ctx, args=inputs)
    exe.forward(is_train=is_train)
    outputs = [x.asnumpy() for x in exe.outputs]
    if len(outputs) == 1:
        outputs = outputs[0]
    return outputs


def _parse_location(symbol, location, ctx):
    assert isinstance(location, (dict, list, tuple))
    if isinstance(location, dict):
        if set(location.keys()) != set(symbol.list_arguments()):
            raise ValueError(
                "Symbol arguments and keys of the given location do not match. symbol args:%s, location.keys():%s"
                % (str(set(symbol.list_arguments())), str(set(location.keys())))
            )
    else:
        location = {k: v for k, v in zip(symbol.list_arguments(), location)}
    return {
        k: array(v, ctx=ctx) if isinstance(v, _np.ndarray) else v for k, v in location.items()
    }


def _parse_aux_states(symbol, aux_states, ctx):
    if aux_states is None:
        return None
    if isinstance(aux_states, dict):
        return {k: array(v, ctx=ctx) if isinstance(v, _np.ndarray) else v
                for k, v in aux_states.items()}
    return {k: array(v, ctx=ctx) for k, v in zip(symbol.list_auxiliary_states(), aux_states)}


def numeric_grad(executor, location, aux_states=None, eps=1e-4, use_forward_train=True):
    """Central finite differences on an executor (parity: test_utils.py numeric_grad)."""
    approx_grads = {k: _np.zeros(v.shape, dtype=_np.float32) for k, v in location.items()}
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    for k in location:
        old_value = location[k].copy()
        for i in range(int(_np.prod(old_value.shape)) if old_value.shape else 1):
            # forward at x+eps/2 and x-eps/2
            flat = old_value.ravel().copy()
            flat[i] += eps / 2.0
            executor.arg_dict[k][:] = flat.reshape(old_value.shape)
            executor.forward(is_train=use_forward_train)
            f_peps = _np.sum(executor.outputs[0].asnumpy())
            flat[i] -= eps
            executor.arg_dict[k][:] = flat.reshape(old_value.shape)
            executor.forward(is_train=use_forward_train)
            f_neps = _np.sum(executor.outputs[0].asnumpy())
            approx_grads[k].ravel()[i] = (f_peps - f_neps) / eps
            executor.arg_dict[k][:] = old_value
    return approx_grads


def check_numeric_gradient(sym_, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None):
    """Finite-difference gradient check (parity: test_utils.py:420)."""
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx)
    location_npy = {k: v.asnumpy() for k, v in location.items()}
    aux_states = _parse_aux_states(sym_, aux_states, ctx)
    if grad_nodes is None:
        grad_nodes = sym_.list_arguments()
        grad_req = {k: "write" for k in grad_nodes}
    elif isinstance(grad_nodes, (list, tuple)):
        grad_req = {k: "write" for k in grad_nodes}
    elif isinstance(grad_nodes, dict):
        grad_req = grad_nodes.copy()
        grad_nodes = grad_nodes.keys()
    else:
        raise ValueError("grad_nodes must be a list, tuple or dict")
    input_shape = {k: v.shape for k, v in location.items()}
    _, out_shape, _ = sym_.infer_shape(**input_shape)
    proj = sym.Variable("__random_proj")
    out = sym.sum(sym_ * proj)
    out = sym.MakeLoss(out)
    location = dict(location)
    # local deterministic stream: an unlucky global-RNG projection can
    # amplify finite-difference error past tolerance for large-Lipschitz
    # ops (observed on `degrees`) — suite policy is deterministic op tests
    prng = _np.random.RandomState(1771)
    location["__random_proj"] = array(
        prng.uniform(-1.0, 1.0, out_shape[0]).astype("float32"), ctx=ctx)
    args_grad_npy = {k: prng.normal(0, 0.01, size=location[k].shape).astype("float32")
                     for k in grad_nodes}
    args_grad = {k: array(v, ctx=ctx) for k, v in args_grad_npy.items()}
    executor = out.bind(ctx, args=location, args_grad=args_grad,
                        aux_states=aux_states, grad_req=grad_req)
    executor.forward(is_train=True)
    executor.backward()
    symbolic_grads = {k: executor.grad_dict[k].asnumpy() for k in grad_nodes}
    numeric_gradients = numeric_grad(
        out.bind(ctx, args={k: v.copy() for k, v in location.items()},
                 aux_states=aux_states),
        {k: v.asnumpy() for k, v in location.items()},
        aux_states, eps=numeric_eps, use_forward_train=use_forward_train,
    )
    for name in grad_nodes:
        fd_grad = numeric_gradients[name]
        sym_grad = symbolic_grads[name]
        if grad_req[name] == "write":
            assert_almost_equal(fd_grad, sym_grad, rtol, atol or 1e-4,
                                ("NUMERICAL_%s" % name, "BACKWARD_%s" % name))
        elif grad_req[name] == "add":
            assert_almost_equal(fd_grad, sym_grad - args_grad_npy[name], rtol, atol or 1e-4,
                                ("NUMERICAL_%s" % name, "BACKWARD_%s" % name))
        elif grad_req[name] == "null":
            assert_almost_equal(args_grad_npy[name], sym_grad, rtol, atol or 1e-4)
        else:
            raise ValueError("Invalid grad_req %s for argument %s" % (grad_req[name], name))


def check_symbolic_forward(sym_, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None):
    """Compare forward vs expected numpy (parity: test_utils.py:533)."""
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx)
    aux_states = _parse_aux_states(sym_, aux_states, ctx)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym_.list_outputs()]
    executor = sym_.bind(ctx, args=location, aux_states=aux_states)
    outputs = executor.forward()
    for output_name, expect, output in zip(sym_.list_outputs(), expected, outputs):
        assert_almost_equal(expect, output.asnumpy(), rtol, atol or 1e-20,
                            ("EXPECTED_%s" % output_name, "FORWARD_%s" % output_name))


def check_symbolic_backward(sym_, location, out_grads, expected, rtol=1e-5, atol=None,
                            aux_states=None, grad_req="write", ctx=None):
    """Compare backward vs expected numpy (parity: test_utils.py:598)."""
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx)
    aux_states = _parse_aux_states(sym_, aux_states, ctx)
    if isinstance(expected, (list, tuple)):
        expected = {k: v for k, v in zip(sym_.list_arguments(), expected)}
    args_grad_npy = {k: _np.random.normal(size=v.shape).astype("float32")
                     for k, v in expected.items()}
    args_grad_data = {k: array(v, ctx=ctx) for k, v in args_grad_npy.items()}
    if isinstance(grad_req, str):
        grad_req = {k: grad_req for k in sym_.list_arguments()}
    elif isinstance(grad_req, (list, tuple)):
        grad_req = {k: v for k, v in zip(sym_.list_arguments(), grad_req)}
    executor = sym_.bind(ctx, args=location, args_grad=args_grad_data,
                         aux_states=aux_states, grad_req=grad_req)
    executor.forward(is_train=True)
    if isinstance(out_grads, (tuple, list)):
        out_grads = [array(v, ctx=ctx) if isinstance(v, _np.ndarray) else v for v in out_grads]
    elif isinstance(out_grads, _np.ndarray):
        out_grads = [array(out_grads, ctx=ctx)]
    executor.backward(out_grads)
    grads = {k: v.asnumpy() for k, v in executor.grad_dict.items()}
    for name in expected:
        if grad_req[name] == "write":
            assert_almost_equal(expected[name], grads[name], rtol, atol or 1e-20,
                                ("EXPECTED_%s" % name, "BACKWARD_%s" % name))
        elif grad_req[name] == "add":
            assert_almost_equal(expected[name], grads[name] - args_grad_npy[name],
                                rtol, atol or 1e-20)
        elif grad_req[name] == "null":
            assert_almost_equal(args_grad_npy[name], grads[name], rtol, atol or 1e-20)


def check_consistency(sym_, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, tol=None, raise_on_err=True):
    """Run the same symbol on several contexts/dtypes and cross-compare
    (parity: test_utils.py check_consistency:765)."""
    if tol is None:
        tol = {_np.dtype(_np.float16): 1e-1, _np.dtype(_np.float32): 1e-3,
               _np.dtype(_np.float64): 1e-5, _np.dtype(_np.uint8): 0,
               _np.dtype(_np.int32): 0}
    elif isinstance(tol, float):
        tol = {_np.dtype(_np.float16): tol, _np.dtype(_np.float32): tol,
               _np.dtype(_np.float64): tol, _np.dtype(_np.uint8): 0,
               _np.dtype(_np.int32): 0}
    assert len(ctx_list) > 1
    if isinstance(sym_, sym.Symbol):
        sym_ = [sym_] * len(ctx_list)
    else:
        assert len(sym_) == len(ctx_list)
    output_names = sym_[0].list_outputs()
    arg_names = sym_[0].list_arguments()
    exe_list = []
    for s, ctx in zip(sym_, ctx_list):
        assert s.list_arguments() == arg_names
        assert s.list_outputs() == output_names
        exe_list.append(s.simple_bind(grad_req=grad_req, **ctx))
    arg_params = {} if arg_params is None else arg_params
    aux_params = {} if aux_params is None else aux_params
    for n, arr in exe_list[0].arg_dict.items():
        if n not in arg_params:
            arg_params[n] = _np.random.normal(size=arr.shape, scale=scale)
    for n, arr in exe_list[0].aux_dict.items():
        if n not in aux_params:
            aux_params[n] = 0
    for exe in exe_list:
        for name, arr in exe.arg_dict.items():
            arr[:] = _np.asarray(arg_params[name]).astype(arr.dtype)
        for name, arr in exe.aux_dict.items():
            arr[:] = _np.asarray(aux_params[name]).astype(arr.dtype)
    dtypes = [_np.dtype(exe.outputs[0].dtype) for exe in exe_list]
    max_idx = _np.argmax([dtypes.index(d) for d in dtypes]) if False else int(
        _np.argmax([d.itemsize for d in dtypes]))
    gt = None
    # forward
    for exe in exe_list:
        exe.forward(is_train=grad_req != "null")
    gt_outputs = [o.asnumpy() for o in exe_list[max_idx].outputs]
    for i, exe in enumerate(exe_list):
        if i == max_idx:
            continue
        rtol = tol[dtypes[i]]
        for name, out, gt_out in zip(output_names, exe.outputs, gt_outputs):
            try:
                assert_almost_equal(out.asnumpy(), gt_out, rtol=rtol, atol=rtol)
            except AssertionError as e:
                print("Predict Err: ctx %d vs ctx %d at %s" % (i, max_idx, name))
                print(e)
                if raise_on_err:
                    raise
    # backward
    if grad_req != "null":
        for exe in exe_list:
            out_grads = [nd.ones(o.shape, ctx=exe._first_ctx) for o in exe.outputs]
            exe.backward(out_grads)
        gt_grads = {n: exe_list[max_idx].grad_dict[n].asnumpy()
                    for n in exe_list[max_idx].grad_dict}
        for i, exe in enumerate(exe_list):
            if i == max_idx:
                continue
            rtol = tol[dtypes[i]]
            for name in exe.grad_dict:
                try:
                    assert_almost_equal(exe.grad_dict[name].asnumpy(), gt_grads[name],
                                        rtol=rtol, atol=rtol)
                except AssertionError as e:
                    print("Train Err: ctx %d vs ctx %d at %s" % (i, max_idx, name))
                    print(e)
                    if raise_on_err:
                        raise
    return gt


def download(url, fname=None, dirname=None, overwrite=False):
    """Fetch `url` to a local file and return its path (reference
    test_utils.py:922).  A file already present (e.g. pre-staged data on
    an air-gapped host) is reused unless overwrite=True; only then is the
    network touched."""
    import os

    if fname is None:
        fname = url.split("/")[-1]
    if dirname is not None:
        os.makedirs(dirname, exist_ok=True)
        fname = os.path.join(dirname, fname)
    if os.path.exists(fname) and not overwrite:
        return fname
    import urllib.request

    try:
        urllib.request.urlretrieve(url, fname)
    except Exception as e:
        raise IOError(
            "download of %s failed (%s). On hosts without egress, stage "
            "the file at %r and it will be used as-is." % (url, e, fname))
    return fname
