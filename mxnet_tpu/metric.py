"""Evaluation metrics (parity: reference python/mxnet/metric.py:27-1057)."""
from __future__ import annotations

import math

import numpy

from .base import numeric_types, string_types
from .ndarray import NDArray

__all__ = [
    "EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy", "F1",
    "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy", "Loss", "Torch",
    "Caffe", "CustomMetric", "np", "create",
]


def check_label_shapes(labels, preds, shape=0):
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            "Shape of labels {} does not match shape of predictions {}".format(label_shape, pred_shape)
        )


class EvalMetric:
    """Base metric (parity: metric.py EvalMetric)."""

    def __init__(self, name, num=None, output_names=None, label_names=None):
        self.name = name
        self.num = num
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num

    def get(self):
        if self.num is None:
            if self.num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.sum_metric / self.num_inst)
        names = ["%s_%d" % (self.name, i) for i in range(self.num)]
        values = [
            x / y if y != 0 else float("nan") for x, y in zip(self.sum_metric, self.num_inst)
        ]
        return (names, values)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics (parity: metric.py CompositeEvalMetric)."""

    def __init__(self, metrics=None, name="composite", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)
        if metrics is None:
            metrics = []
        self.metrics = [create(m) if isinstance(m, str) else m for m in metrics]

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str) else metric)

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError("Metric index {} is out of range 0 and {}".format(index, len(self.metrics)))

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        names = []
        results = []
        for metric in self.metrics:
            result = metric.get()
            name = result[0]
            if isinstance(name, string_types):
                name = [name]
                result = [result[1]]
            else:
                result = result[1]
            names.extend(name)
            results.extend(result)
        return (names, results)


class Accuracy(EvalMetric):
    """Classification accuracy (parity: metric.py Accuracy)."""

    def __init__(self, axis=1, name="accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred_np = pred_label.asnumpy() if isinstance(pred_label, NDArray) else numpy.asarray(pred_label)
            label_np = label.asnumpy() if isinstance(label, NDArray) else numpy.asarray(label)
            # parity: argmax whenever prediction and label shapes differ
            # (reference metric.py Accuracy — handles (N,1) column labels too)
            if pred_np.shape != label_np.shape:
                pred_np = numpy.argmax(pred_np, axis=self.axis)
            label_np = label_np.astype("int32")
            pred_np = pred_np.astype("int32")
            check_label_shapes(label_np.flat, pred_np.flat)
            self.sum_metric += (pred_np.flat == label_np.flat).sum()
            self.num_inst += len(pred_np.flat)


class TopKAccuracy(EvalMetric):
    """Top-k accuracy (parity: metric.py TopKAccuracy)."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred_np = numpy.argsort(
                (pred_label.asnumpy() if isinstance(pred_label, NDArray) else pred_label).astype("float32")
            )
            label_np = (label.asnumpy() if isinstance(label, NDArray) else numpy.asarray(label)).astype("int32")
            check_label_shapes(label_np, pred_np, 0)
            num_samples = pred_np.shape[0]
            num_dims = len(pred_np.shape)
            if num_dims == 1:
                self.sum_metric += (pred_np.flat == label_np.flat).sum()
            elif num_dims == 2:
                num_classes = pred_np.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (pred_np[:, num_classes - 1 - j].flat == label_np.flat).sum()
            self.num_inst += num_samples


class F1(EvalMetric):
    """Binary F1 (parity: metric.py F1)."""

    def __init__(self, name="f1", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred_np = pred.asnumpy() if isinstance(pred, NDArray) else numpy.asarray(pred)
            label_np = (label.asnumpy() if isinstance(label, NDArray) else numpy.asarray(label)).astype("int32")
            pred_label = numpy.argmax(pred_np, axis=1)
            check_label_shapes(label_np, pred_np)
            if len(numpy.unique(label_np)) > 2:
                raise ValueError("F1 currently only supports binary classification.")
            true_positives, false_positives, false_negatives = 0.0, 0.0, 0.0
            for y_pred, y_true in zip(pred_label, label_np):
                if y_pred == 1 and y_true == 1:
                    true_positives += 1.0
                elif y_pred == 1 and y_true == 0:
                    false_positives += 1.0
                elif y_pred == 0 and y_true == 1:
                    false_negatives += 1.0
            if true_positives + false_positives > 0:
                precision = true_positives / (true_positives + false_positives)
            else:
                precision = 0.0
            if true_positives + false_negatives > 0:
                recall = true_positives / (true_positives + false_negatives)
            else:
                recall = 0.0
            if precision + recall > 0:
                f1_score = 2 * precision * recall / (precision + recall)
            else:
                f1_score = 0.0
            self.sum_metric += f1_score
            self.num_inst += 1


class Perplexity(EvalMetric):
    """Perplexity (parity: metric.py Perplexity)."""

    def __init__(self, ignore_label, axis=-1, name="perplexity", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label_np = label.asnumpy() if isinstance(label, NDArray) else numpy.asarray(label)
            pred_np = pred.asnumpy() if isinstance(pred, NDArray) else numpy.asarray(pred)
            assert label_np.size == pred_np.size / pred_np.shape[-1], (
                "shape mismatch: %s vs. %s" % (label_np.shape, pred_np.shape)
            )
            label_flat = label_np.reshape((label_np.size,)).astype("int32")
            probs = pred_np.reshape((-1, pred_np.shape[-1]))[numpy.arange(label_flat.size), label_flat]
            if self.ignore_label is not None:
                ignore = (label_flat == self.ignore_label).astype(probs.dtype)
                num -= int(numpy.sum(ignore))
                probs = probs * (1 - ignore) + ignore
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += label_flat.size
        self.sum_metric += numpy.exp(loss / num) * num if False else loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label_np = label.asnumpy() if isinstance(label, NDArray) else numpy.asarray(label)
            pred_np = pred.asnumpy() if isinstance(pred, NDArray) else numpy.asarray(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            self.sum_metric += numpy.abs(label_np - pred_np).mean()
            self.num_inst += 1


class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label_np = label.asnumpy() if isinstance(label, NDArray) else numpy.asarray(label)
            pred_np = pred.asnumpy() if isinstance(pred, NDArray) else numpy.asarray(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            self.sum_metric += ((label_np - pred_np) ** 2.0).mean()
            self.num_inst += 1


class RMSE(EvalMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label_np = label.asnumpy() if isinstance(label, NDArray) else numpy.asarray(label)
            pred_np = pred.asnumpy() if isinstance(pred, NDArray) else numpy.asarray(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            self.sum_metric += numpy.sqrt(((label_np - pred_np) ** 2.0).mean())
            self.num_inst += 1


class CrossEntropy(EvalMetric):
    """Cross entropy of softmax output vs int labels (parity: metric.py CrossEntropy)."""

    def __init__(self, eps=1e-8, name="cross-entropy", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label_np = (label.asnumpy() if isinstance(label, NDArray) else numpy.asarray(label)).ravel()
            pred_np = pred.asnumpy() if isinstance(pred, NDArray) else numpy.asarray(pred)
            assert label_np.shape[0] == pred_np.shape[0]
            prob = pred_np[numpy.arange(label_np.shape[0]), numpy.int64(label_np)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label_np.shape[0]


class Loss(EvalMetric):
    """Mean of the output itself (parity: metric.py Loss)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, _, preds):
        for pred in preds:
            pred_np = pred.asnumpy() if isinstance(pred, NDArray) else numpy.asarray(pred)
            self.sum_metric += pred_np.sum()
            self.num_inst += pred_np.size


class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)


class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)


class CustomMetric(EvalMetric):
    """Metric from a python function (parity: metric.py CustomMetric)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False, output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names=output_names, label_names=label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label_np = label.asnumpy() if isinstance(label, NDArray) else numpy.asarray(label)
            pred_np = pred.asnumpy() if isinstance(pred, NDArray) else numpy.asarray(pred)
            reval = self._feval(label_np, pred_np)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (parity: metric.py np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, **kwargs):
    """Create by name or callable or list (parity: metric.py create)."""
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite_metric = CompositeEvalMetric()
        for child_metric in metric:
            composite_metric.add(create(child_metric, **kwargs))
        return composite_metric
    metrics = {
        "acc": Accuracy, "accuracy": Accuracy, "ce": CrossEntropy,
        "f1": F1, "mae": MAE, "mse": MSE, "rmse": RMSE,
        "top_k_accuracy": TopKAccuracy, "topkaccuracy": TopKAccuracy,
        "perplexity": Perplexity, "loss": Loss, "torch": Torch, "caffe": Caffe,
        "cross-entropy": CrossEntropy, "crossentropy": CrossEntropy,
        "composite": CompositeEvalMetric,
    }
    try:
        return metrics[metric.lower()](**kwargs)
    except Exception:
        raise ValueError("Metric must be either callable or in {}".format(sorted(metrics.keys())))
