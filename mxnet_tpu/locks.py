"""Runtime lock-contract verifier — the dynamic half of mxlint E008/E009.

The static pass (tools/analysis/lock_checks.py) proves lock-order
consistency for the acquisition sites it can see in one file; this
module is the runtime teeth for everything it cannot: cross-module
nesting, callback-driven acquisition, and the production question
"which lock is everyone actually waiting on?".

Every subsystem declares its locks through the three factories here
instead of calling ``threading.Lock()`` directly::

    self._lock = locks.lock("serving.server")
    self._cv = locks.condition("serving.queue")          # own hidden lock
    self._work_cv = locks.condition("engine", self._lock)  # shared lock

With ``MXTPU_LOCK_CHECK`` unset (the default) the factories return the
plain ``threading`` primitives — zero overhead, byte-identical
behavior.  With ``MXTPU_LOCK_CHECK=1`` they return a
:class:`RecordingLock` that

* keeps a per-thread held-set and folds every held->acquired pair into
  a process-global lock ORDER graph;
* detects a cycle at edge-insertion time — i.e. BEFORE blocking on the
  lock that would complete the deadlock — and raises (or, under
  ``MXTPU_LOCK_CHECK_ACTION=dump``, records + prints) a
  :class:`DeadlockError` postmortem naming BOTH conflicting
  acquisition sites;
* books ``locks.wait_seconds.<name>`` / ``locks.hold_seconds.<name>``
  histograms and a ``locks.contended`` counter into the telemetry
  registry (E004-guarded), and emits a ``lock_wait.<name>`` span while
  the profiler runs, so contention renders beside the dispatch lanes
  in chrome traces and ``parse_log --telemetry``.

Deliberately NOT converted: the telemetry/profiler registry locks
themselves (a RecordingLock books telemetry, so instrumenting the
registry's own lock would recurse) — both are leaf locks by
construction, documented in docs/observability.md.

Chaos pin: tests/test_locks.py scripts an AB/BA deadlock that raises
in milliseconds with the check on and genuinely hangs with it off.
"""
from __future__ import annotations

import sys
import threading
import time

from . import config

__all__ = ["DeadlockError", "RecordingLock", "lock", "rlock", "condition",
           "enabled", "order_graph", "cycles", "violations", "held_names",
           "reset"]


class DeadlockError(RuntimeError):
    """A lock acquisition would close a cycle in the global order graph.

    ``sites`` carries the two conflicting acquisition sites:
    ``(this_site, prior_site)`` — where THIS thread is taking ``b``
    while holding ``a``, and where some earlier acquisition took ``a``
    (possibly transitively) while holding ``b``.
    """

    def __init__(self, msg, a=None, b=None, sites=()):
        super().__init__(msg)
        self.a = a
        self.b = b
        self.sites = tuple(sites)


def enabled():
    """True when MXTPU_LOCK_CHECK=1 — factories hand out RecordingLocks."""
    return bool(config.get("MXTPU_LOCK_CHECK"))


# ---------------------------------------------------------------------------
# process-global order graph
# ---------------------------------------------------------------------------

# raw leaf lock guarding the graph — NEVER a RecordingLock (recursion)
_STATE_LOCK = threading.Lock()
# name -> {successor_name: (outer_site, inner_site)} with first-seen sites;
# edge a->b means "b was acquired while a was held"
_EDGES = {}
# postmortems recorded instead of raised under MXTPU_LOCK_CHECK_ACTION=dump
_VIOLATIONS = []
_TLS = threading.local()


def _held():
    """This thread's held list: [(RecordingLock, site_str), ...]."""
    lst = getattr(_TLS, "held", None)
    if lst is None:
        lst = _TLS.held = []
    return lst


_SKIP_PREFIXES = tuple(s[:-1] if s.endswith("c") else s
                       for s in (__file__, threading.__file__))


def _site():
    """'file:line' of the acquiring frame — first caller outside this
    module and the threading internals.  Walks raw frames
    (sys._getframe) rather than traceback.extract_stack(): this runs
    on EVERY sentinel acquire, and extract_stack's per-frame linecache
    lookups dominate the <5% overhead budget (bench --serve --lock-ab
    measures it)."""
    f = sys._getframe(1)
    while f is not None:
        fname = f.f_code.co_filename
        if not fname.startswith(_SKIP_PREFIXES):
            return "%s:%d" % (fname, f.f_lineno)
        f = f.f_back
    return "<unknown>"


def _reaches(src, dst):
    """Path of names src -> ... -> dst in _EDGES, or None.  Caller holds
    _STATE_LOCK."""
    stack = [(src, (src,))]
    seen = set()
    while stack:
        cur, path = stack.pop()
        if cur == dst:
            return path
        if cur in seen:
            continue
        seen.add(cur)
        for nxt in _EDGES.get(cur, ()):
            stack.append((nxt, path + (nxt,)))
    return None


def _postmortem(holder_name, holder_site, taking_name, taking_site, path):
    """Render the two-sided DeadlockError message: this acquisition and
    the recorded reverse-path edge that closes the cycle."""
    first = _EDGES.get(path[0], {}).get(path[1], ("<unknown>", "<unknown>"))
    chain = " -> ".join(path)
    held = ", ".join("%r held since %s" % (lk.name, s) for lk, s in _held())
    return (
        "lock order violation: acquiring %r at %s while holding %r "
        "(acquired at %s), but the order graph already has %s — "
        "recorded when %r was taken under %r at %s (outer acquisition "
        "at %s).  This thread holds: [%s].  Consistent order or a "
        "`# mxlint: disable=E008 -- why` justification required."
        % (taking_name, taking_site, holder_name, holder_site, chain,
           path[1], path[0], first[1], first[0], held))


def _on_violation(msg, a, b, sites):
    action = config.get("MXTPU_LOCK_CHECK_ACTION")
    err = DeadlockError(msg, a=a, b=b, sites=sites)
    if action == "dump":
        with _STATE_LOCK:
            _VIOLATIONS.append(err)
        from . import telemetry
        if telemetry.enabled():
            telemetry.inc("locks.order_violations")
        print("MXTPU_LOCK_CHECK: %s" % msg, file=sys.stderr)
        return
    raise err


class RecordingLock:
    """Drop-in threading.Lock/RLock replacement that records ordering.

    Satisfies the full ``threading.Condition`` owner-lock protocol via
    the stdlib's documented fallbacks (plain ``acquire(0)`` probe for
    ``_is_owned``, release/acquire for the wait-side save/restore), so
    ``threading.Condition(RecordingLock(...))`` works unchanged.
    """

    def __init__(self, name, recursive=False):
        self.name = name
        self._recursive = recursive
        self._inner = threading.RLock() if recursive else threading.Lock()
        self._acquired_at = {}  # thread ident -> hold-start perf time

    # -- ordering ----------------------------------------------------------

    def _depths(self):
        d = getattr(_TLS, "depths", None)
        if d is None:
            d = _TLS.depths = {}
        return d

    def _record(self, site):
        """Fold (held -> self) edges into the global graph; raise/dump
        on a cycle BEFORE the caller blocks on the inner lock."""
        held = _held()
        if not held:
            return
        # lock-free fast path: edges only ever grow (reset() swaps the
        # whole dict), so if every held lock already has its (holder ->
        # self) edge recorded there is nothing to fold in — the common
        # steady-state acquire never touches _STATE_LOCK
        name = self.name
        for holder, _hs in held:
            if holder is not self and holder.name != name \
                    and name not in _EDGES.get(holder.name, ()):
                break
        else:
            return
        with _STATE_LOCK:
            pending = []
            for holder, holder_site in held:
                # same-name siblings (per-connection / per-replica locks
                # share one factory name) are ordering CLASSES, not
                # instances — nesting two is not self-deadlock evidence
                if holder is self or holder.name == self.name:
                    continue
                succ = _EDGES.setdefault(holder.name, {})
                if self.name not in succ:
                    path = _reaches(self.name, holder.name)
                    if path is not None:
                        msg = _postmortem(holder.name, holder_site,
                                          self.name, site, path)
                        sites = (site,
                                 _EDGES[path[0]].get(path[1],
                                                     ("?", "?"))[1])
                        pending.append((msg, holder.name, sites))
                        continue
                    succ[self.name] = (holder_site, site)
        for msg, holder_name, sites in pending:
            _on_violation(msg, a=holder_name, b=self.name, sites=sites)

    # -- lock protocol -----------------------------------------------------

    def acquire(self, blocking=True, timeout=-1):
        me = threading.get_ident()
        depths = self._depths()
        if self._recursive and depths.get(id(self), 0) > 0:
            got = self._inner.acquire(blocking, timeout)
            if got:
                depths[id(self)] += 1
            return got
        site = _site()
        self._record(site)
        t0 = time.perf_counter()
        got = self._inner.acquire(False)
        contended = not got
        if not got:
            from . import profiler, telemetry
            if telemetry.enabled():
                telemetry.inc("locks.contended")
            if not blocking:
                if telemetry.enabled():
                    telemetry.observe("locks.wait_seconds.%s" % self.name,
                                      time.perf_counter() - t0)
                return False
            got = self._inner.acquire(True, timeout)
        wait = time.perf_counter() - t0
        if contended:
            from . import profiler, telemetry
            if telemetry.enabled():
                telemetry.observe("locks.wait_seconds.%s" % self.name, wait)
            if profiler.spans_active():
                profiler.record_span("lock_wait.%s" % self.name,
                                     int((time.time() - wait) * 1e6),
                                     int(wait * 1e6), cat="lock")
        if got:
            depths[id(self)] = 1
            self._acquired_at[me] = time.perf_counter()
            _held().append((self, site))
        return got

    def release(self):
        me = threading.get_ident()
        depths = self._depths()
        if self._recursive and depths.get(id(self), 0) > 1:
            depths[id(self)] -= 1
            self._inner.release()
            return
        t_acq = self._acquired_at.pop(me, None)
        if t_acq is not None:
            from . import telemetry
            if telemetry.enabled():
                telemetry.observe("locks.hold_seconds.%s" % self.name,
                                  time.perf_counter() - t_acq)
        depths.pop(id(self), None)
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                break
        self._inner.release()

    # -- threading.Condition owner-lock protocol ---------------------------
    # Without these the stdlib falls back to an acquire(False) probe for
    # _is_owned, which a RecordingLock would mis-book as contention.

    def _is_owned(self):
        return self._depths().get(id(self), 0) > 0

    def _release_save(self):
        n = self._depths().get(id(self), 0)
        for _ in range(max(1, n)):
            self.release()
        return n

    def _acquire_restore(self, state):
        for _ in range(max(1, state)):
            self.acquire()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else self._depths().get(id(self), 0) > 0

    def __repr__(self):
        return "<RecordingLock %r%s>" % (self.name,
                                         " (recursive)" if self._recursive
                                         else "")


# ---------------------------------------------------------------------------
# factories — THE declared lock sites call these (docs/static_analysis.md
# "lock naming convention": dotted subsystem.role names)
# ---------------------------------------------------------------------------

def lock(name):
    """A mutex named for telemetry/ordering; plain Lock when the check
    is off."""
    return RecordingLock(name) if enabled() else threading.Lock()


def rlock(name):
    """Reentrant variant of :func:`lock`."""
    return RecordingLock(name, recursive=True) if enabled() \
        else threading.RLock()


def condition(name, lock=None):
    """A condition variable; pass ``lock`` to share an existing
    factory-made lock (the engine's one-lock/two-conditions layout) —
    condition waits then count against that lock's name."""
    if lock is None and enabled():
        lock = RecordingLock(name)
    return threading.Condition(lock)


# ---------------------------------------------------------------------------
# introspection (tests, bench.py --lock-ab, postmortem tooling)
# ---------------------------------------------------------------------------

def order_graph():
    """Copy of the global order graph:
    {name: {successor: (outer_site, inner_site)}}."""
    with _STATE_LOCK:
        return {a: dict(succ) for a, succ in _EDGES.items()}


def cycles():
    """Unordered lock pairs {a, b} that are mutually reachable in the
    order graph — each is a latent deadlock (empty list = clean run).
    Under ACTION=raise a cycle raises before its edge lands, so this
    reports cycles observed in dump mode or via racing edge inserts."""
    with _STATE_LOCK:
        out, seen = [], set()
        for a, succ in _EDGES.items():
            for b in succ:
                key = frozenset((a, b))
                if key in seen:
                    continue
                if _reaches(b, a):
                    seen.add(key)
                    out.append(sorted(key))
        return out


def violations():
    """DeadlockErrors recorded under MXTPU_LOCK_CHECK_ACTION=dump."""
    with _STATE_LOCK:
        return list(_VIOLATIONS)


def held_names():
    """Names of locks the CALLING thread currently holds (debugging)."""
    return [lk.name for lk, _ in _held()]


def reset():
    """Clear the order graph + recorded violations (tests; per-thread
    held-sets empty themselves as locks release)."""
    with _STATE_LOCK:
        _EDGES.clear()
        del _VIOLATIONS[:]
