"""Async distributed snapshots — the write half of ``mxnet_tpu.ckpt``.

Design (docs/checkpoint.md): at a dispatch boundary each rank captures
the full training state D2H — params/aux off the executor (a read of the
post-update arrays, never the donated inputs), the name-keyed optimizer
state (``Updater.states``), the lr-scheduler counters, both host RNG
streams, and the data cursor ``(epoch, batch_index)`` — then hands the
serialized payload to a BACKGROUND engine op (the ``serve_stage``
pattern, serving/session.py: ``atomic=False`` push with an in-band
error queue) that writes the shard file tmp-then-rename.  The file I/O
overlaps the next K-step dispatches; the training thread only ever
blocks on the PREVIOUS write, at the next trigger, by which point it has
almost always finished.

Commit is deferred by one trigger: once every rank's shard for step S is
drained (and, multi-process, a ``sync_global_devices`` barrier proves
it cluster-wide), rank 0 renames ``manifest-sS.json.tmp`` into place —
the checkpoint exists from that instant and never before.  A kill at
ANY point leaves either the previous committed checkpoint or the new
one, never a torn restore (ckpt/atomic.py).

State identity across ranks: on the data-parallel mesh every process
holds the full (replicated) param/optimizer host copy and — by the SPMD
seed contract (every rank seeds ``HOST_RNG`` identically and draws one
seed per dispatch in lockstep, executor._next_seed) — the identical RNG
stream.  Every rank therefore writes a complete shard, and ANY subset
of survivors can restore from any one of them: the redundancy the
elastic shrink path (ckpt/elastic.py) rides.
"""
from __future__ import annotations

import os
import pickle
import queue as _queue
import time

from ..base import MXNetError
from . import atomic

__all__ = ["CheckpointManager", "capture_state"]


def _rank_count():
    """(process_index, process_count) — (0, 1) for a single-process run
    (jax.process_index works unconditionally once a backend exists, and
    by first-snapshot time the training stack has long initialized it)."""
    import jax

    return jax.process_index(), jax.process_count()


def capture_state(module, epoch, batch_index, step):
    """One rank's complete resume state as a host-side dict (all numpy /
    plain python — nothing in the payload keeps a device buffer alive).

    The D2H read happens here, synchronously, OFF the donated-buffer
    path: ``get_params`` reads the executor's post-update arrays (the
    dispatch outputs, not its donated inputs) and the Updater's state
    leaves were written back host-side by the same dispatch."""
    import numpy as np

    from ..ops.random_ops import GLOBAL_RNG, HOST_RNG

    if not (module.binded and module.params_initialized):
        raise MXNetError("cannot snapshot an unbound/uninitialized module")
    args, auxs = module.get_params()
    updater = getattr(module, "_updater", None)
    if module.optimizer_initialized and updater is None:
        raise MXNetError(
            "checkpointing the kvstore-side update path is not supported: "
            "optimizer state lives on the servers (use kvstore=None, the "
            "fused-dispatch path, for elastic training)")
    opt = getattr(module, "_optimizer", None)
    payload = {
        "format": atomic.MANIFEST_FORMAT,
        "step": int(step),
        "epoch": int(epoch),
        "batch_index": int(batch_index),
        "args": {k: np.asarray(v.asnumpy()) for k, v in args.items()},
        "auxs": {k: np.asarray(v.asnumpy()) for k, v in auxs.items()},
        "updater": updater.get_states() if updater is not None else None,
        "opt": None if opt is None else {
            "num_update": int(opt.num_update),
            "begin_num_update": int(opt.begin_num_update),
            "index_update_count": dict(opt._index_update_count),
        },
        "host_rng": HOST_RNG.get_state(),
        "global_rng": GLOBAL_RNG.get_state(),
    }
    return payload


def _mesh_desc(module):
    mesh = getattr(module, "_mesh", None)
    if mesh is None:
        return None
    return {"axes": list(mesh.axis_names),
            "shape": [int(mesh.shape[a]) for a in mesh.axis_names]}


class CheckpointManager:
    """Arm periodic async snapshots on a training loop.

    ``Module.fit`` drives it: :meth:`note_dispatch` after every device
    dispatch (snapshot when the step budget is due), :meth:`epoch_end`
    at each epoch boundary (commit + elastic regrow-yield check),
    :meth:`finalize` when the loop exits.  All ranks of an SPMD job must
    drive the SAME manager schedule — triggers align by determinism of
    the dispatch sequence, and the commit barrier assumes it.

    Knobs (config.py): ``MXTPU_CKPT_DIR`` / ``_EVERY_STEPS`` / ``_KEEP``
    / ``_ASYNC``; constructor args override.
    """

    def __init__(self, directory=None, every_steps=None, keep=None,
                 async_write=None, data_seed=0, knobs=None):
        from .. import config

        self.directory = (directory if directory is not None
                          else config.get("MXTPU_CKPT_DIR"))
        self.every_steps = int(every_steps if every_steps is not None
                               else config.get("MXTPU_CKPT_EVERY_STEPS"))
        self.keep = int(keep if keep is not None
                        else config.get("MXTPU_CKPT_KEEP"))
        self.async_write = bool(async_write if async_write is not None
                                else config.get("MXTPU_CKPT_ASYNC"))
        self.enabled = bool(self.directory) and self.every_steps > 0
        self.data_seed = int(data_seed)
        self.knobs = dict(knobs or {})
        self.yielded = False
        self._global_step = 0
        self._last_snap = 0
        self._var = None          # engine var serializing the write ops
        self._pending = None      # (step, handoff queue) of the in-flight write
        self._commit_step = None  # step whose manifest awaits rename
        if self.enabled:
            os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------------
    # trigger plumbing
    # ------------------------------------------------------------------
    def set_global_step(self, step):
        """Seed the step counter after a resume so snapshot cadence (and
        shard/manifest names) continue the interrupted run's sequence."""
        self._global_step = int(step)
        self._last_snap = int(step)

    def note_dispatch(self, module, epoch, batch_index, steps=1):
        """Called once per device dispatch; `batch_index` is the count
        of batches CONSUMED so far this epoch (the resume cursor)."""
        self._global_step += int(steps)
        if not self.enabled:
            return
        if self._global_step - self._last_snap >= self.every_steps:
            self.snapshot(module, epoch, batch_index)

    def snapshot(self, module, epoch, batch_index):
        """Take one snapshot now: drain+commit the previous write, then
        schedule this step's shard write in the background."""
        if not self.enabled:
            return
        self._drain_commit()
        self._last_snap = self._global_step
        self._write(module, epoch, batch_index, self._global_step)

    def epoch_end(self, module, next_epoch):
        """Epoch-boundary service: commit any pending snapshot, then —
        if an elastic regrow was requested (ckpt/elastic.py) — cut a
        boundary checkpoint at ``(next_epoch, 0)`` and mark the manager
        yielded so the caller can exit for the full-width relaunch."""
        if not self.enabled:
            return
        self._drain_commit()
        from . import elastic

        if elastic.regrow_requested(self.directory):
            if self._global_step > self._last_snap or not atomic.list_manifests(self.directory):
                self._last_snap = self._global_step
                self._write(module, next_epoch, 0, self._global_step)
            self._drain_commit()
            self.yielded = True

    def finalize(self):
        """Commit whatever write is still in flight (fit exit path)."""
        if self.enabled:
            self._drain_commit()

    # ------------------------------------------------------------------
    # the async write + deferred commit
    # ------------------------------------------------------------------
    def _write(self, module, epoch, batch_index, step):
        from .. import engine, telemetry
        from ..obs import recorder

        rank, nranks = _rank_count()
        t0 = time.time()
        payload = capture_state(module, epoch, batch_index, step)
        blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        # census: the D2H blob rides host RAM until the async write
        # retires it — book here, unbook in _io's finally below
        blob_booked = 0
        if telemetry.enabled():
            from ..obs import memory

            blob_booked = len(blob)
            memory.book("ckpt_blobs", blob_booked)
        if telemetry.enabled():
            telemetry.inc("ckpt.snapshots")
            telemetry.observe("ckpt.d2h_seconds", time.time() - t0)
            telemetry.set_gauge("ckpt.last_step", step)
        if recorder.enabled():
            # post-mortem attribution: "which snapshot was in flight";
            # the exit lands at commit time (_drain_commit)
            recorder.record("ckpt", "enter", step,
                            detail="snapshot(e%d,b%d)" % (epoch, batch_index),
                            nbytes=len(blob))
        spath = atomic.shard_path(self.directory, rank, step)
        manifest = None
        if rank == 0:
            manifest = {
                "format": atomic.MANIFEST_FORMAT,
                "step": step, "epoch": int(epoch),
                "batch_index": int(batch_index),
                "seed": self.data_seed,
                "nranks": nranks,
                "mesh_shape": _mesh_desc(module),
                "knobs": dict(self.knobs,
                              steps_per_dispatch=getattr(
                                  module, "_steps_per_dispatch", 1),
                              every_steps=self.every_steps),
                "shards": [os.path.basename(
                    atomic.shard_path(self.directory, r, step))
                    for r in range(nranks)],
                "wall_time": time.time(),
            }
        handoff = _queue.Queue(1)
        mpath = atomic.manifest_path(self.directory, step)

        def _io(_blob=blob, _spath=spath, _manifest=manifest, _mpath=mpath,
                _q=handoff, _booked=blob_booked):
            # errors travel in-band (serve_stage convention): a deferred
            # engine error would leave the trainer blocked on the
            # handoff at the next drain forever
            try:
                import json as _json

                t0 = time.time()
                n = atomic.write_bytes(_spath, _blob)
                if _manifest is not None:
                    # the manifest is STAGED (tmp file), not committed:
                    # the rename is the host thread's commit act, after
                    # the cluster-wide barrier proves every shard landed
                    with open(_mpath + ".tmp", "w") as f:
                        _json.dump(_manifest, f, indent=2, sort_keys=True)
                        f.flush()
                        os.fsync(f.fileno())
                if telemetry.enabled():
                    telemetry.inc("ckpt.bytes", n)
                    telemetry.observe("ckpt.write_seconds",
                                      time.time() - t0)
                _q.put(None)
            except BaseException as e:  # pragma: no cover - error path
                _q.put(e)
            finally:
                if _booked:
                    from ..obs import memory

                    memory.unbook("ckpt_blobs", _booked)

        if self.async_write:
            if self._var is None:
                self._var = engine.new_variable()
            engine.push(_io, write_vars=(self._var,), atomic=False,
                        name="ckpt_write")
        else:
            _io()
        self._pending = (step, handoff)
        self._commit_step = step
        if not self.async_write:
            self._drain_commit()

    def _drain_commit(self):
        """Block on the in-flight shard write (usually long done — it
        overlapped the dispatches since), then commit its manifest:
        barrier so every rank's shard is durable, rank-0 renames."""
        if self._pending is not None:
            step, handoff = self._pending
            err = handoff.get()
            self._pending = None
            if err is not None:
                raise MXNetError("checkpoint shard write for step %d "
                                 "failed: %s" % (step, err))
        if self._commit_step is None:
            return
        step, self._commit_step = self._commit_step, None
        rank, nranks = _rank_count()
        if nranks > 1:
            from ..parallel import multihost

            # every rank reaches here with its shard durable; after the
            # barrier rank 0 knows ALL shards are, and may commit.  A
            # COORDINATION-SERVICE barrier, deliberately: the next
            # dispatch's gradient all-reduce is usually still in flight
            # on the gloo pairs, and a device-collective barrier would
            # interleave with it (multihost.coordination_barrier)
            multihost.coordination_barrier("ckpt_commit_s%d" % step)
        if rank == 0:
            mpath = atomic.manifest_path(self.directory, step)
            os.replace(mpath + ".tmp", mpath)
            atomic.prune(self.directory, self.keep)
        from .. import telemetry
        from ..obs import recorder

        if telemetry.enabled():
            telemetry.inc("ckpt.commits")
        if recorder.enabled():
            recorder.record("ckpt", "exit", step)
