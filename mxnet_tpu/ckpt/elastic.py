"""Elastic occupancy — mesh shrink on rank death, regrow at epoch
boundaries.

The division of labor (docs/checkpoint.md "Elastic workflow"):

* ``tools/launch.py --elastic`` is the SUPERVISOR: it watches the rank
  processes it spawned; when one dies mid-run (SIGKILL, OOM) it reaps
  the survivors (they may be wedged in a collective with the dead peer
  — the watchdog's ``LivenessBook``/stall postmortem names the culprit,
  but recovery is membership change, not in-place repair), then
  relaunches the job at N−1 with a fresh coordinator and
  ``MXTPU_ELASTIC_GENERATION`` bumped.  Each new generation re-enters
  ``multihost.initialize`` with the reduced world and resumes from the
  last committed manifest (``MXTPU_CKPT_RESUME``).

* THIS module is the in-framework half: generation accounting, the
  regrow request sentinel, and the yield exit code that lets a shrunken
  generation hand its slots back at an epoch boundary so the supervisor
  can relaunch at full width.

Why the batch sequence survives the shrink: the data service's epoch
order is a pure function of ``(seed, epoch)`` and the consumer
reassembles batches in GLOBAL batch-index order, worker-count invariant
(data/worker.py epoch_order); params/optimizer state are replicated on
the data axes, so any survivor subset restores the full state from any
shard.  N−1 survivors therefore replay the IDENTICAL global batch and
loss sequence the N-rank run would have produced — the tier-1 elastic
chaos pin (tests/test_ckpt_elastic.py).
"""
from __future__ import annotations

import os

__all__ = ["YIELD_EXIT_CODE", "generation", "request_regrow",
           "regrow_requested", "clear_regrow", "dead_ranks"]

# a shrunken generation that checkpointed at an epoch boundary and wants
# the supervisor to relaunch it at full width exits with this code; it
# must stay in lockstep with _ELASTIC_YIELD_RC in tools/launch.py
YIELD_EXIT_CODE = 3

_REGROW_SENTINEL = "regrow.request"


def generation():
    """This process's elastic generation (0 = the original launch);
    bumped by the supervisor on every relaunch."""
    return int(os.environ.get("MXTPU_ELASTIC_GENERATION", "0"))


def _sentinel(directory):
    return os.path.join(directory, _REGROW_SENTINEL)


def request_regrow(directory):
    """Ask the running (shrunken) job to yield at its next epoch
    boundary so the supervisor can relaunch at full width.  Written by
    the supervisor when a replacement slot is available; read by
    ``CheckpointManager.epoch_end``."""
    with open(_sentinel(directory), "w") as f:
        f.write("regrow\n")


def regrow_requested(directory):
    return bool(directory) and os.path.exists(_sentinel(directory))


def clear_regrow(directory):
    try:
        os.unlink(_sentinel(directory))
    except OSError:
        pass


def dead_ranks(book):
    """Ranks a ``parallel.dist.LivenessBook`` currently names dead or
    unclean — the watchdog/postmortem's answer to "who do we shrink
    around".  The supervisor ALSO sees deaths directly (it owns the
    processes); the book is the in-band view for ranks that want to log
    or gate on membership before the supervisor reaps them."""
    gone = set(book.dead())
    gone.update(book.unclean())
    return sorted(gone)
