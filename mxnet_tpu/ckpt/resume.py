"""Exact resume — the read half of ``mxnet_tpu.ckpt``.

``Module.fit(resume_from=...)`` lands here: :func:`load` picks the
newest committed manifest (or an explicit one), :func:`apply` puts the
global arrays back onto the mesh (``Module.set_params`` → the executor
placement path → ``mesh.global_put`` for sharded params), restores the
name-keyed optimizer state and the lr-scheduler counters, and replays
both host RNG streams, and :func:`fast_forward` advances the data
pipeline to ``batch_index`` — for the sharded data service by the PURE
epoch function (workers recompute ``epoch_order(seed, epoch)`` and jump,
zero decode), generically by consuming batches.

The contract is bit-identity, not approximation: after apply +
fast_forward, every subsequent dispatch sees the identical params,
optimizer state, lr, dropout seed, and batch bytes the uninterrupted
run would have seen, so the loss trajectory is equal EXACTLY (the tier-1
resume-parity pin, tests/test_ckpt.py).  The one sequence that cannot
be replayed is an epoch-cumulative eval metric across the kill point —
a mid-epoch resume restarts the metric accumulation at the resume
batch (docs/checkpoint.md).
"""
from __future__ import annotations

import os
import pickle

from ..base import MXNetError
from . import atomic

__all__ = ["ResumeState", "load", "apply", "fast_forward"]


class ResumeState:
    """One loaded checkpoint: the commit record + this rank's payload."""

    def __init__(self, manifest, payload, manifest_file):
        self.manifest = manifest
        self.payload = payload
        self.manifest_file = manifest_file
        self.step = int(manifest["step"])
        self.epoch = int(manifest["epoch"])
        self.batch_index = int(manifest["batch_index"])


def _pick_shard(directory, manifest, manifest_file):
    """This rank's shard if the manifest names one, else shard 0: on the
    data mesh every shard carries the complete replicated state and the
    identical SPMD RNG stream (ckpt/snapshot.py), so a shrunken or
    re-ranked survivor set restores from whatever is on disk."""
    import jax

    shards = manifest.get("shards") or []
    if not shards:
        raise MXNetError("manifest '%s' names no shards" % manifest_file)
    rank = jax.process_index()
    name = shards[rank] if rank < len(shards) else shards[0]
    path = os.path.join(directory, name)
    if not os.path.exists(path):
        path = os.path.join(directory, shards[0])
    return path


def load(path, required=True):
    """Resolve `path` (a checkpoint directory or an explicit manifest
    file) to a :class:`ResumeState`.  ``required=False`` returns None
    for a directory with no committed checkpoint yet — the elastic
    supervisor's "resume if there is anything to resume" contract
    (``MXTPU_CKPT_RESUME``)."""
    if os.path.isdir(path):
        directory = path
        manifest_file = atomic.latest_manifest(directory)
        if manifest_file is None:
            if required:
                raise MXNetError(
                    "no committed checkpoint in '%s' (a manifest-s*.json "
                    "is the unit of validity; shard files alone are an "
                    "interrupted snapshot)" % directory)
            return None
    else:
        manifest_file = path
        directory = os.path.dirname(os.path.abspath(path))
    manifest = atomic.read_manifest(manifest_file)
    shard = _pick_shard(directory, manifest, manifest_file)
    try:
        with open(shard, "rb") as f:
            payload = pickle.load(f)
    except FileNotFoundError:
        raise MXNetError("checkpoint shard '%s' named by manifest '%s' is "
                         "missing" % (shard, manifest_file))
    except Exception as e:
        raise MXNetError("checkpoint shard '%s' is truncated or corrupt "
                         "(%s) — committed shards rename atomically, so "
                         "this file was damaged after the fact"
                         % (shard, e))
    if payload.get("format") != atomic.MANIFEST_FORMAT:
        raise MXNetError("shard '%s' is not a %s payload"
                         % (shard, atomic.MANIFEST_FORMAT))
    if int(payload["step"]) != int(manifest["step"]):
        raise MXNetError("shard '%s' is step %s but manifest '%s' is step "
                         "%s — mixed checkpoint directories?"
                         % (shard, payload["step"], manifest_file,
                            manifest["step"]))
    return ResumeState(manifest, payload, manifest_file)


def apply(module, state):
    """Restore `module` (bound, params+optimizer initialized) from
    `state`; returns ``(epoch, batch_index)`` — the cursor fit resumes
    at.  Ordering matters: params go to the device first (set_params →
    global_put placement), then optimizer state and scheduler counters
    (the fused dispatch re-places its state leaves lazily), then the RNG
    streams, so the very next ``_next_seed`` draw continues the
    interrupted run's sequence bit-exactly."""
    from .. import telemetry
    from ..ndarray import array
    from ..ops.random_ops import GLOBAL_RNG, HOST_RNG

    payload = state.payload
    args = {k: array(v) for k, v in payload["args"].items()}
    auxs = {k: array(v) for k, v in payload["auxs"].items()}
    module.set_params(args, auxs, allow_missing=False, force_init=True,
                      allow_extra=False)
    updater = getattr(module, "_updater", None)
    if payload.get("updater") is not None:
        if updater is None:
            raise MXNetError(
                "checkpoint at '%s' carries optimizer state but this "
                "module has no host-side updater (kvstore update path); "
                "resume with kvstore=None" % state.manifest_file)
        updater.set_states(payload["updater"])
    opt = getattr(module, "_optimizer", None)
    if opt is not None and payload.get("opt") is not None:
        rec = payload["opt"]
        # the lr/wd schedule is a pure function of these counters
        # (optimizer._get_lr via lr_scheduler(num_update)): restoring
        # them IS the scheduler replay
        opt.num_update = int(rec["num_update"])
        opt.begin_num_update = int(rec["begin_num_update"])
        opt._index_update_count.clear()
        opt._index_update_count.update(rec["index_update_count"])
    HOST_RNG.set_state(payload["host_rng"])
    GLOBAL_RNG.set_state(payload["global_rng"])
    if telemetry.enabled():
        telemetry.inc("ckpt.resumes")
        telemetry.set_gauge("ckpt.resume_step", state.step)
    return state.epoch, state.batch_index


def fast_forward(data_iter, epoch, nskip):
    """Advance `data_iter` to batch `nskip` of `epoch`.  Iterators that
    expose ``seek_epoch(epoch, start_batch)`` (ShardedImageRecordIter —
    the data service jumps by the pure epoch function, skipping decode
    entirely) seek directly; anything else consumes ``nskip`` batches,
    which is equivalent because the epoch sequence is deterministic."""
    seek = getattr(data_iter, "seek_epoch", None)
    if callable(seek):
        seek(epoch, nskip)
        return
    for _ in range(int(nskip)):
        try:
            data_iter.next()
        except StopIteration:
            break
