"""mxnet_tpu.ckpt — elastic fault-tolerant training (docs/checkpoint.md).

Four modules:

* :mod:`~mxnet_tpu.ckpt.atomic`   — write-then-rename artifacts + the
  ``mxtpu-ckpt-v1`` manifest (a checkpoint exists iff its manifest
  renamed; no torn restores).
* :mod:`~mxnet_tpu.ckpt.snapshot` — async per-rank shard writes
  overlapped with the next K-step dispatch (background engine op), with
  rank-0 deferred manifest commit behind a cluster barrier.
* :mod:`~mxnet_tpu.ckpt.resume`   — ``Module.fit(resume_from=)``: exact
  restore of params/optimizer/RNG/lr counters + pure-function data
  fast-forward; the resumed loss trajectory is bit-identical.
* :mod:`~mxnet_tpu.ckpt.elastic`  — shrink to N−1 on rank death and
  regrow at epoch boundaries, driven by the ``tools/launch.py
  --elastic`` supervisor.
"""
from __future__ import annotations

from . import atomic, elastic, resume, snapshot
from .atomic import latest_manifest, list_manifests, read_manifest
from .resume import ResumeState, load
from .snapshot import CheckpointManager, capture_state

__all__ = ["atomic", "snapshot", "resume", "elastic", "CheckpointManager",
           "capture_state", "ResumeState", "load", "latest_manifest",
           "list_manifests", "read_manifest"]
