"""Atomic artifact writes + the ``mxtpu-ckpt-v1`` manifest surface.

Every durable artifact this framework emits rides one idiom: write the
full payload to a sibling ``.tmp`` path, then ``os.replace`` it over the
final name (the BANDWIDTH.json / watchdog-postmortem pattern —
obs/watchdog.py, tools/bandwidth/measure.py).  ``os.replace`` is atomic
on POSIX within a filesystem, so readers observe either the previous
complete artifact or the new complete artifact, never a torn prefix —
the property the whole checkpoint design rests on: a checkpoint EXISTS
iff its manifest renamed, and the manifest renames only after every
shard it names is durably on disk.

Layout of one checkpoint directory::

    <dir>/shard-r00000-s0000000012.ckpt   per-rank payload (pickle)
    <dir>/shard-r00001-s0000000012.ckpt
    <dir>/manifest-s0000000012.json       rank-0 commit record

The manifest is the unit of validity.  Shard files without a manifest
are garbage from an interrupted snapshot (pruned on the next commit);
a ``manifest-*.json.tmp`` is a commit that never happened and is
ignored by :func:`list_manifests`.
"""
from __future__ import annotations

import contextlib
import json
import os
import re

from ..base import MXNetError

__all__ = ["MANIFEST_FORMAT", "replace_into", "write_bytes", "write_json",
           "shard_path", "manifest_path", "list_manifests",
           "latest_manifest", "read_manifest", "prune"]

MANIFEST_FORMAT = "mxtpu-ckpt-v1"

_MANIFEST_RE = re.compile(r"^manifest-s(\d{10})\.json$")
_SHARD_RE = re.compile(r"^shard-r(\d{5})-s(\d{10})\.ckpt$")


@contextlib.contextmanager
def replace_into(path):
    """Yield a temporary sibling path; on clean exit ``os.replace`` it
    over `path`, on exception unlink it.  The tmp name keeps the final
    extension as a SUFFIX of the basename prefix (``name.ext.tmp``), so
    a crashed writer's leftovers are recognizable and never match the
    artifact globs above."""
    tmp = path + ".tmp"
    try:
        yield tmp
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def write_bytes(path, data):
    """Atomically write `data` to `path` (fsync'd before the rename, so
    the commit ordering shard-then-manifest survives a host crash, not
    just a process kill)."""
    with replace_into(path) as tmp:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
    return len(data)


def write_json(path, obj):
    return write_bytes(path, (json.dumps(obj, indent=2, sort_keys=True)
                              + "\n").encode("utf-8"))


def shard_path(directory, rank, step):
    return os.path.join(directory, "shard-r%05d-s%010d.ckpt"
                        % (int(rank), int(step)))


def manifest_path(directory, step):
    return os.path.join(directory, "manifest-s%010d.json" % int(step))


def list_manifests(directory):
    """All COMMITTED checkpoints in `directory`, sorted by step:
    ``[(step, path), ...]``.  ``.tmp`` leftovers (a commit that never
    renamed) are invisible by construction of the name pattern."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for n in names:
        m = _MANIFEST_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, n)))
    out.sort()
    return out


def latest_manifest(directory):
    """Path of the newest committed manifest, or None."""
    manifests = list_manifests(directory)
    return manifests[-1][1] if manifests else None


def read_manifest(path):
    """Parse + validate one manifest; raises MXNetError naming the file
    on a missing/garbled/foreign artifact instead of a raw traceback."""
    try:
        with open(path, "rb") as f:
            manifest = json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        raise MXNetError("checkpoint manifest '%s' does not exist" % path)
    except (ValueError, OSError) as e:
        raise MXNetError("checkpoint manifest '%s' is unreadable or "
                         "corrupt (%s) — it should be impossible for a "
                         "kill to tear a committed manifest; was the "
                         "file edited or copied partially?" % (path, e))
    if manifest.get("format") != MANIFEST_FORMAT:
        raise MXNetError("'%s' is not a %s manifest (format=%r)"
                         % (path, MANIFEST_FORMAT, manifest.get("format")))
    return manifest


def prune(directory, keep):
    """Drop all but the newest `keep` committed checkpoints.  Deletion
    order is the commit order REVERSED — manifest first, so a kill
    mid-prune leaves orphan shards (garbage, collected next prune), never
    a manifest naming missing shards.  Also sweeps shard files whose
    step has no manifest at all (an interrupted snapshot's leftovers,
    EXCEPT steps newer than the newest manifest — those may be a commit
    in flight)."""
    manifests = list_manifests(directory)
    keep = max(1, int(keep))
    dead = manifests[:-keep] if len(manifests) > keep else []
    live_steps = {s for s, _ in manifests[len(dead):]}
    newest = manifests[-1][0] if manifests else -1
    for step, path in dead:
        with contextlib.suppress(OSError):
            os.unlink(path)
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for n in names:
        m = _SHARD_RE.match(n)
        if m and int(m.group(2)) not in live_steps and int(m.group(2)) <= newest:
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(directory, n))
