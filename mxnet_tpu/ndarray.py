"""NDArray — the imperative tensor.

TPU-native equivalent of the reference NDArray
(reference include/mxnet/ndarray.h:59-436, src/ndarray/ndarray.cc).

Architecture mapping (SURVEY.md §7 phase 2):
  * The reference NDArray is a view over a ref-counted Chunk holding a
    Storage handle plus a dependency-engine variable; every op is pushed to
    the ThreadedEngine with declared read/write sets.  Here the payload is a
    `jax.Array`: PJRT's async dispatch + XLA's data-flow ordering provide
    exactly the engine's read-after-write guarantees, and `wait_to_read` ≙
    `block_until_ready` (reference WaitToRead, ndarray.h:297).
  * Mutation (`a[:] = x`, `a += b`) is functional underneath: the wrapped
    buffer is replaced.  Donated-buffer aliasing inside jitted executors
    recovers in-place update performance (SURVEY.md §7 hard-part 1).
  * `Slice`/`At` views (reference ndarray.h:267-311) are write-through:
    a view records (parent, index); reads slice the parent lazily, writes
    scatter into the parent — preserving the reference's aliasing semantics
    without aliased device memory.
  * Imperative op invoke (reference MXImperativeInvoke,
    src/c_api/c_api_ndarray.cc:248-430) becomes: unbox args → registered
    JAX fn (eager, per-primitive compile cache ≙ CuDNNAlgoReg) → box.
"""
from __future__ import annotations

import builtins
import functools
import struct
import sys

import numpy as _np

import jax
import jax.numpy as jnp

from .base import MXNetError
from .context import Context, cpu, current_context
from .ops.registry import OP_REGISTRY, get_op
from . import engine

__all__ = ["NDArray", "array", "empty", "zeros", "ones", "full", "arange",
           "concatenate", "moveaxis", "load", "loads", "save", "waitall",
           "imresize", "onehot_encode", "from_dlpack"]

_DTYPE_ALIASES = {None: jnp.float32}

# installed by contrib.autograd: callable(replay_fn, in_ndarrays, out_ndarrays)
# recording imperative ops onto the autograd tape when a train_section is
# active (reference AutogradRuntime::RecordImperativeFCompute,
# src/ndarray/autograd.cc:82)
_RECORD_HOOK = None


def _as_jax(value, dtype=None):
    if isinstance(value, NDArray):
        return value.data
    if isinstance(value, jax.Array):
        return value
    return jnp.asarray(value, dtype=dtype)


def _snapshot(value):
    """Freeze a raw (non-NDArray) operand to an immutable jax.Array at
    its call-site value — THE snapshot rule for every deferred use of a
    caller-owned buffer (engine dispatch args/kwargs, autograd replay
    constants, lazy-chain inputs).  copy=True is load-bearing: plain
    jnp.asarray on CPU may zero-copy ALIAS numpy memory, which is no
    snapshot at all."""
    return jnp.array(value, copy=True) if isinstance(value, _np.ndarray) \
        else _as_jax(value)


class NDArray:
    """Multi-dimensional array on a device (parity: python/mxnet/ndarray.py NDArray)."""

    # _fresh_grad backs MXNDArray{Set,Get}GradState (set lazily; unset
    # slot reads as 0 through the C API).  _var is the engine dependency
    # variable for this chunk (reference NDArray::var(), ndarray.h:350),
    # created lazily on first engine dispatch.  _lazy is the pending
    # deferred-op node producing this chunk under lazy imperative
    # evaluation (lazy.py), or None once materialized/flushed.
    __slots__ = ("_data", "_ctx", "_parent", "_index", "writable",
                 "_fresh_grad", "_var", "_lazy", "_mem_booked")

    def __init__(self, data, ctx=None, _parent=None, _index=None):
        self._parent = _parent
        self._index = _index
        self._ctx = ctx if ctx is not None else current_context()
        self._data = data
        self._var = None
        self._lazy = None
        self._mem_booked = 0
        self.writable = True
        if data is not None and _parent is None:
            self._mem_account(data)

    def _mem_account(self, value):
        """Live-buffer census (obs/memory.py, tag ``ndarray.<device>``):
        book this chunk's payload bytes at every payload swap.  The
        booked amount is recorded on the chunk so __del__ releases
        exactly what was booked — the census stays balanced even when
        telemetry toggles mid-life.  Views book nothing (the parent
        owns the payload)."""
        from . import telemetry

        if not telemetry.enabled():
            return
        from .obs import memory

        n = int(getattr(value, "nbytes", 0) or 0)
        booked = self._mem_booked
        if n != booked:
            memory.rebook("ndarray." + self._ctx.device_type, booked, n)
            self._mem_booked = n

    def __del__(self):
        booked = getattr(self, "_mem_booked", 0)
        if booked:
            try:
                from .obs import memory

                memory.unbook("ndarray." + self._ctx.device_type, booked)
            except Exception:
                pass  # interpreter teardown: books are gone anyway

    # ------------------------------------------------------------------
    # payload access
    # ------------------------------------------------------------------
    @property
    def data(self):
        """The underlying jax.Array (lazy slice of parent for views).

        This is a READ sync point: if engine ops are pending on this
        chunk's variable the read blocks until the writers complete (and
        re-raises their deferred error — reference WaitToRead semantics).
        Inside an engine op the wait is skipped: the op's declared deps
        already guarantee the value is final."""
        if self._parent is not None:
            return self._parent.data[self._index]
        if self._lazy is not None:
            # lazy sync point: push the pending fused chain through the
            # engine; the wait below then blocks on its write token
            lazy.materialize(self)
        var = self._var
        if var is not None and (var.pending_writes or var.exception is not None) \
                and not engine.in_engine_op():
            engine.get().wait_for_var(var)
        if self._data is None and var is not None:
            # the producing engine op failed and its deferred error was
            # already delivered at an earlier sync point; a clear error
            # beats an AttributeError on a None payload downstream
            raise MXNetError(
                "NDArray value is unavailable: the engine op that was to "
                "produce it failed (its error was raised at an earlier "
                "sync point)")
        engine.note_access(var, False)  # SanitizerEngine contract check
        return self._data

    def _raw(self):
        """Payload WITHOUT engine sync — only valid inside an engine op
        whose declared read/write vars cover this array (the
        SanitizerEngine verifies exactly that via note_access)."""
        if self._parent is not None:
            return self._parent._raw()[self._index]
        engine.note_access(self._var, False)
        return self._data

    def _engine_var(self):
        """This chunk's dependency variable (reference NDArray::var();
        views share their parent's var, as reference views share the
        Chunk).  Requesting the var is how a chunk enters the
        engine-visible world, so any pending fused chain touching it is
        flushed first — its tokens must exist before a foreign op's
        tokens order against them."""
        if self._parent is not None:
            return self._parent._engine_var()
        lazy.flush_for_array(self)
        if self._var is None:
            self._var = engine.Var()
        return self._var

    def _full_overwrite_base(self):
        """Current payload for a whole-array overwrite, or None when there
        is none to preserve (the producing op failed): inside an engine op
        the raw payload is authoritative; outside, pending writers are
        awaited first so a not-yet-delivered producer error still raises
        here rather than being silently papered over."""
        if self._parent is not None:
            return self.data
        if self._lazy is not None:
            return self.data  # lazy sync point: flush + wait
        if engine.in_engine_op():
            return self._raw()
        var = self._var
        if var is not None and (var.pending_writes or var.exception is not None):
            return self.data  # waits; re-raises an undelivered deferred error
        return self._data

    def _set_data(self, value):
        if self._parent is not None:
            self._parent._set_data(self._parent.data.at[self._index].set(value))
        else:
            if not engine.in_engine_op():
                # mutation sync point: pending fused chains reading (or
                # producing) this chunk must be pushed first so their
                # read tokens order BEFORE this write (lazy analog of
                # the WAR wait below); inside an engine op the flush
                # already happened at push time (_engine_var)
                lazy.flush_for_array(self)
            var = self._var
            if var is not None and (var.pending_writes or var.pending_reads) \
                    and not engine.in_engine_op():
                # in-place assignment is a WRITE on the chunk var: wait out
                # pending readers (WAR) and writers (WAW) before swapping
                engine.get().wait_for_var(var, wait_reads=True)
            engine.note_access(var, True)  # SanitizerEngine contract check
            self._data = value
            self._mem_account(value)

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    def _meta_aval(self):
        """Abstract shape/dtype of a pending lazy value, or None —
        metadata reads must not flush a fused chain (lazy.aval_for)."""
        if self._parent is None and self._lazy is not None:
            return lazy.aval_for(self)
        return None

    @property
    def shape(self):
        aval = self._meta_aval()
        if aval is not None:
            return tuple(aval.shape)
        return tuple(self.data.shape)

    @property
    def size(self):
        aval = self._meta_aval()
        if aval is not None:
            return int(_np.prod(aval.shape)) if aval.shape else 1
        return int(self.data.size)

    @property
    def ndim(self):
        aval = self._meta_aval()
        if aval is not None:
            return len(aval.shape)
        return self.data.ndim

    @property
    def dtype(self):
        aval = self._meta_aval()
        if aval is not None:
            return _np.dtype(aval.dtype)
        return _np.dtype(self.data.dtype)

    @property
    def context(self):
        return self._ctx

    @property
    def ctx(self):
        return self._ctx

    @property
    def T(self):
        return NDArray(self.data.T, self._ctx)

    def __len__(self):
        return self.shape[0]

    def __repr__(self):
        return "<NDArray %s @%s>" % ("x".join(map(str, self.shape)), self._ctx)

    def __str__(self):
        return str(self.asnumpy())

    # ------------------------------------------------------------------
    # DLPack interop (reference include/mxnet/ndarray.h:401 SetDLTensor;
    # zero-copy exchange with numpy/torch/jax ecosystems)
    # ------------------------------------------------------------------
    def __array__(self, dtype=None, copy=None):
        # numpy interop: np.asarray(nd) is one bulk transfer, not a
        # per-element __getitem__ walk
        if copy is False:
            raise ValueError(
                "NDArray->numpy always copies (device-to-host transfer); "
                "copy=False cannot be honored")
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, *args, **kwargs):
        return self.data.__dlpack__(*args, **kwargs)

    def __dlpack_device__(self):
        return self.data.__dlpack_device__()

    def to_dlpack_for_read(self):
        """The array itself — any DLPack consumer accepts it via
        `from_dlpack(nd)` (capsule protocol)."""
        return self

    to_dlpack_for_write = to_dlpack_for_read

    # ------------------------------------------------------------------
    # host transfer / sync (reference WaitToRead / asnumpy)
    # ------------------------------------------------------------------
    def asnumpy(self):
        d = self.data
        if isinstance(d, jax.Array) and not d.is_fully_addressable \
                and not d.is_fully_replicated:
            # a batch-sharded GLOBAL array (multi-process mesh): remote
            # shards must be allgathered before a host read — collective,
            # so every process's training loop reaches here in the same
            # order (SPMD); see parallel/multihost.fetch
            from .parallel.multihost import fetch

            return fetch(d)
        return _np.asarray(d)

    def asscalar(self):
        return self.asnumpy().reshape(()).item()

    def wait_to_read(self):
        """Block until this array's value is computed (reference WaitToRead).

        Two fences compose: the engine's `wait_for_var` drains pending
        host-side ops on this chunk's variable, then the device fence
        covers XLA's own async dispatch.  On tunneled/relay device
        platforms (axon) `block_until_ready` can return before execution
        finishes; there a 1-element host transfer is the reliable fence.
        Healthy local platforms keep the transfer-free fence."""
        self._sync(wait_reads=False)

    def wait_to_write(self):
        """Block until pending readers AND writers finish (reference
        WaitToWrite): after this, an in-place mutation cannot race a
        queued engine op."""
        self._sync(wait_reads=True)

    def _sync(self, wait_reads):
        base = self
        while base._parent is not None:
            base = base._parent
        # lazy sync point (wait_to_read/wait_to_write): push the pending
        # chain producing or reading this chunk before fencing its var
        lazy.flush_for_array(base)
        if base._var is not None:
            engine.get().wait_for_var(base._var, wait_reads=wait_reads)
        d = self.data
        if hasattr(d, "block_until_ready"):
            d.block_until_ready()
        if _needs_scalar_fence() and d.size:
            jax.device_get(d.ravel()[0])

    # ------------------------------------------------------------------
    # conversion / copies
    # ------------------------------------------------------------------
    def astype(self, dtype):
        return NDArray(self.data.astype(jnp.dtype(dtype)), self._ctx)

    def copy(self):
        return NDArray(self.data + 0, self._ctx)

    def copyto(self, other):
        """Copy into an NDArray or to a Context (reference ndarray.h CopyFromTo)."""
        if isinstance(other, NDArray):
            other[:] = self
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self.data, other.jax_device()), other)
        raise TypeError("copyto does not support type " + str(type(other)))

    def as_in_context(self, ctx):
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    def reshape(self, shape, **kwargs):
        if isinstance(shape, int):
            shape = (shape,)
        return NDArray(jnp.reshape(self.data, tuple(shape)), self._ctx)

    def broadcast_to(self, shape):
        return NDArray(jnp.broadcast_to(self.data, tuple(shape)), self._ctx)

    # ------------------------------------------------------------------
    # views (reference Slice/At are zero-copy aliases; here write-through)
    # ------------------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key.data.astype(jnp.int32)
            return NDArray(self.data[key], self._ctx)
        return NDArray(None, self._ctx, _parent=self, _index=key)

    def __setitem__(self, key, value):
        # NOTE: builtins.slice — the registry populates a module-level `slice`
        # op function in this namespace, which would shadow the builtin here.
        if isinstance(key, builtins.slice) and key == builtins.slice(None):
            base = self._full_overwrite_base()
            if base is None:
                # revival of a failed array (its producer op errored and the
                # deferred error was already delivered): a full overwrite
                # needs no prior value — this is how e.g. kv.pull restores
                # a poisoned weight, and how the engine's
                # write-clears-poison rule stays reachable
                newval = _as_jax(value)
                if getattr(newval, "ndim", 0) == 0:
                    raise MXNetError(
                        "cannot restore a failed NDArray from a scalar: its "
                        "shape was never materialized; assign a full array")
                self._set_data(newval)
                return
            val = _as_jax(value, dtype=base.dtype)
            self._set_data(jnp.broadcast_to(val, base.shape).astype(base.dtype))
            return
        val = _as_jax(value, dtype=self.dtype)
        if isinstance(key, NDArray):
            key = key.data.astype(jnp.int32)
        self._set_data(self.data.at[key].set(val))

    def slice(self, start, stop):
        return self[start:stop]

    def at(self, idx):
        return self[idx]

    # ------------------------------------------------------------------
    # arithmetic — dispatches through the op registry so imperative and
    # symbolic share one definition (SURVEY.md §7 phase 2)
    # ------------------------------------------------------------------
    def _binary(self, other, op_name, scalar_name, reverse=False):
        if isinstance(other, _np.ndarray) and other.ndim == 0:
            other = float(other)
        if isinstance(other, (NDArray, jax.Array, _np.ndarray)):
            args = (other, self) if reverse else (self, other)
            out = _engine_invoke(get_op(op_name), args, {}, self._ctx)
            if _RECORD_HOOK is not None:
                fn = get_op(op_name).fn
                if isinstance(other, NDArray):
                    ins = [other, self] if reverse else [self, other]
                    _RECORD_HOOK(fn, ins, [out])
                else:
                    # raw operand captured as a replay constant — the
                    # replay must see call-site values
                    const = _snapshot(other)
                    if reverse:
                        _RECORD_HOOK(lambda x, _c=const, _f=fn: _f(_c, x),
                                     [self], [out])
                    else:
                        _RECORD_HOOK(lambda x, _c=const, _f=fn: _f(x, _c),
                                     [self], [out])
            return out
        out = _engine_invoke(get_op(scalar_name), (self,),
                             {"scalar": float(other)}, self._ctx)
        if _RECORD_HOOK is not None:
            _RECORD_HOOK(lambda x, _f=get_op(scalar_name).fn, _s=float(other):
                         _f(x, scalar=_s), [self], [out])
        return out

    def __add__(self, o):
        return self._binary(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        if isinstance(o, NDArray):
            return o.__sub__(self)
        return NDArray(get_op("_rminus_scalar").fn(self.data, scalar=float(o)), self._ctx)

    def __mul__(self, o):
        return self._binary(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elemwise_div", "_div_scalar")

    __div__ = __truediv__

    def __rtruediv__(self, o):
        if isinstance(o, NDArray):
            return o.__truediv__(self)
        return NDArray(get_op("_rdiv_scalar").fn(self.data, scalar=float(o)), self._ctx)

    __rdiv__ = __rtruediv__

    def __mod__(self, o):
        return self._binary(o, "_mod", "_mod_scalar")

    def __pow__(self, o):
        return self._binary(o, "_power", "_power_scalar")

    def __rpow__(self, o):
        return NDArray(get_op("_rpower_scalar").fn(self.data, scalar=float(o)), self._ctx)

    def __neg__(self):
        return NDArray(-self.data, self._ctx)

    def __iadd__(self, o):
        self._set_data((self + o).data.astype(self.dtype))
        return self

    def __isub__(self, o):
        self._set_data((self - o).data.astype(self.dtype))
        return self

    def __imul__(self, o):
        self._set_data((self * o).data.astype(self.dtype))
        return self

    def __itruediv__(self, o):
        self._set_data((self / o).data.astype(self.dtype))
        return self

    __idiv__ = __itruediv__

    def __eq__(self, o):
        return self._binary(o, "_equal", "_equal_scalar") if o is not None else False

    def __ne__(self, o):
        return self._binary(o, "_not_equal", "_not_equal_scalar") if o is not None else True

    def __gt__(self, o):
        return self._binary(o, "_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple elements is ambiguous.")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __getstate__(self):
        return {"data": self.asnumpy(), "ctx": (self._ctx.device_type, self._ctx.device_id)}

    def __setstate__(self, state):
        self._parent = None
        self._index = None
        self._var = None
        self._lazy = None
        self._ctx = Context(*state["ctx"])
        self._data = jnp.asarray(state["data"])
        self._mem_booked = 0
        self.writable = True
        self._mem_account(self._data)

    # convenience reductions mirroring generated methods
    def sum(self, axis=None, keepdims=False):
        return NDArray(jnp.sum(self.data, axis=axis, keepdims=keepdims), self._ctx)

    def mean(self, axis=None, keepdims=False):
        return NDArray(jnp.mean(self.data, axis=axis, keepdims=keepdims), self._ctx)

    def max(self, axis=None, keepdims=False):
        return NDArray(jnp.max(self.data, axis=axis, keepdims=keepdims), self._ctx)

    def min(self, axis=None, keepdims=False):
        return NDArray(jnp.min(self.data, axis=axis, keepdims=keepdims), self._ctx)

    def abs(self):
        return NDArray(jnp.abs(self.data), self._ctx)

    def flatten(self):
        return NDArray(self.data.reshape((self.shape[0], -1)), self._ctx)

    def expand_dims(self, axis):
        return NDArray(jnp.expand_dims(self.data, axis), self._ctx)

    def transpose(self, axes=None):
        return NDArray(jnp.transpose(self.data, axes), self._ctx)

    def argmax(self, axis=None):
        return NDArray(jnp.argmax(self.data, axis=axis).astype(jnp.float32), self._ctx)


# lazy imperative evaluation (deferred-op fusion) — imported AFTER the
# NDArray class: lazy.py imports NDArray back from this module
from . import lazy  # noqa: E402


# ----------------------------------------------------------------------
# creation routines (parity: python/mxnet/ndarray.py module functions)
# ----------------------------------------------------------------------


# NOTE on placement: creation returns UNCOMMITTED jax arrays — XLA places
# them on the default device and freely co-locates with other operands.
# Committing every array to its Context's device (the reference model,
# where NDArray memory is physically on ctx) would poison mixed-context
# arithmetic under JAX's committed-device rules.  Explicit placement
# happens in exactly two places: Executor mesh shardings and copyto().


def array(source_array, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(source_array, NDArray):
        source_array = source_array.data
    host_source = not isinstance(source_array, jax.Array)
    if dtype is None and not isinstance(source_array, (_np.ndarray, jax.Array)):
        dtype = "float32"  # parity: python lists default to float32
    arr = jnp.asarray(source_array, dtype=jnp.dtype(dtype) if dtype else None)
    if arr.dtype == jnp.float64:
        arr = arr.astype(jnp.float32)
    if host_source:
        # the real host->device transfer point of the imperative API
        # (batch iterators, init, user numpy): telemetry counts H2D
        # bytes HERE, where the copy happens, not at forward()
        from . import telemetry

        if telemetry.enabled():
            telemetry.inc("executor.h2d_bytes", int(arr.nbytes))
    return NDArray(arr, ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx, dtype)


def _norm_shape(shape):
    return shape if isinstance(shape, tuple) else (shape,) if isinstance(shape, int) else tuple(shape)


def zeros(shape, ctx=None, dtype=None):
    ctx = ctx or current_context()
    return NDArray(jnp.zeros(_norm_shape(shape), dtype=jnp.dtype(dtype) if dtype else jnp.float32), ctx)


def ones(shape, ctx=None, dtype=None):
    ctx = ctx or current_context()
    return NDArray(jnp.ones(_norm_shape(shape), dtype=jnp.dtype(dtype) if dtype else jnp.float32), ctx)


def full(shape, val, ctx=None, dtype=None):
    ctx = ctx or current_context()
    return NDArray(jnp.full(_norm_shape(shape), val, dtype=jnp.dtype(dtype) if dtype else jnp.float32), ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    arr = get_op("_arange").fn(start=start, stop=stop, step=step, repeat=repeat,
                               dtype=dtype or "float32")
    ctx = ctx or current_context()
    return NDArray(arr, ctx)


def moveaxis(tensor, source, destination):
    """Move `tensor`'s axis `source` to position `destination`
    (reference ndarray.py:1166)."""
    return NDArray(jnp.moveaxis(tensor.data, int(source), int(destination)),
                   tensor.ctx)


def concatenate(arrays, axis=0, always_copy=True):
    return NDArray(jnp.concatenate([a.data for a in arrays], axis=axis), arrays[0].ctx)


def onehot_encode(indices, out):
    depth = out.shape[1]
    out[:] = NDArray(jax.nn.one_hot(indices.data.astype(jnp.int32), depth), indices.ctx)
    return out


def imresize(src, w, h, interp=1):
    out = jax.image.resize(src.data, (h, w) + src.shape[2:], method="bilinear" if interp else "nearest")
    return NDArray(out, src.ctx)


_SCALAR_FENCE = None


def _needs_scalar_fence():
    """True when running through the axon relay, where block_until_ready is
    not a real completion fence (measured: 'completed' 8192^3 matmuls in
    0.03 ms)."""
    global _SCALAR_FENCE
    if _SCALAR_FENCE is None:
        _SCALAR_FENCE = "axon" in str(getattr(jax.config, "jax_platforms", "") or "")
    return _SCALAR_FENCE


def waitall():
    """Global fence (reference Engine::WaitForAll).

    Drains the dependency engine (all pushed NDArray/kvstore/io ops),
    re-raising the first deferred engine error, then fences the device:
    JAX has no global work queue to drain, so we fence a fresh
    computation, which on an in-order device stream completes after all
    prior work."""
    lazy.flush_all("sync")
    engine.get().wait_for_all()
    x = jnp.zeros(()) + 0
    x.block_until_ready()
    if _needs_scalar_fence():
        jax.device_get(x)


# ----------------------------------------------------------------------
# serialization (parity: mx.nd.save/load → reference src/c_api/c_api.cc:218-271)
#
# Default on-disk layout is the REFERENCE binary NDArray-list format so
# .params files interop with upstream MXNet both directions:
#   u64 magic=0x112 (kMXAPINDArrayListMagic), u64 reserved=0,
#   u64 count, per array (NDArray::Save, src/ndarray/ndarray.cc:641-664):
#   u32 NDARRAY_V1_MAGIC, u32 ndim + i64 dims (V1 int64 TShape),
#   Context (i32 dev_type, i32 dev_id), i32 type_flag, raw bytes;
#   then u64 nkeys + (u64 len + bytes) per key.  Load also accepts the
#   pre-V1 legacy TShape layout (u32 ndim + u32 dims,
#   LegacyTShapeLoad ndarray.cc:666-682).
# Arrays whose dtype the reference ABI cannot express (bfloat16, int64, ...)
# or 0-dim arrays (reference Load treats ndim==0 as a none-NDArray and
# stops reading, ndarray.cc:688-690) fall back to the self-describing
# MXTPU001 container; load() sniffs both.
# ----------------------------------------------------------------------

_SAVE_MAGIC = b"MXTPU001"
_NDLIST_MAGIC = 0x112  # kMXAPINDArrayListMagic
_NDARRAY_V1_MAGIC = 0xF993FAC8  # per-array magic, int64 TShape
_DTYPE_TO_FLAG = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3, "int32": 4}
_FLAG_TO_DTYPE = {v: k for k, v in _DTYPE_TO_FLAG.items()}


def _split_save_arg(data):
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        keys = list(data.keys())
        arrays = [data[k] for k in keys]
    else:
        keys = None
        arrays = list(data)
    np_arrays = [a.asnumpy() if isinstance(a, NDArray) else _np.asarray(a)
                 for a in arrays]
    return keys, np_arrays


def from_dlpack(ext_array, ctx=None):
    """Zero-copy import of any DLPack-capable array (torch/numpy/jax/...)."""
    from .context import current_context

    return NDArray(jnp.from_dlpack(ext_array), ctx or current_context())


def save(fname, data):
    """Save list or dict of NDArray (parity: python/mxnet/ndarray.py save)."""
    keys, np_arrays = _split_save_arg(data)
    if all(a.dtype.name in _DTYPE_TO_FLAG and a.ndim > 0 for a in np_arrays):
        return _save_reference_format(fname, keys, np_arrays)
    return _save_container_format(fname, keys, np_arrays)


def _save_reference_format(fname, keys, np_arrays):
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQ", _NDLIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(np_arrays)))
        for np_arr in np_arrays:
            f.write(struct.pack("<II", _NDARRAY_V1_MAGIC, np_arr.ndim))
            f.write(struct.pack("<%dq" % np_arr.ndim, *np_arr.shape))
            f.write(struct.pack("<ii", 1, 0))  # Context: kCPU, dev_id 0
            f.write(struct.pack("<i", _DTYPE_TO_FLAG[np_arr.dtype.name]))
            f.write(_np.ascontiguousarray(np_arr).tobytes())
        names = keys if keys is not None else []
        f.write(struct.pack("<Q", len(names)))
        for name in names:
            b = name.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def _save_container_format(fname, keys, np_arrays):
    with open(fname, "wb") as f:
        f.write(_SAVE_MAGIC)
        f.write(struct.pack("<q", len(np_arrays)))
        f.write(struct.pack("<q", 1 if keys is not None else 0))
        for i, np_arr in enumerate(np_arrays):
            name = (keys[i] if keys is not None else "").encode()
            f.write(struct.pack("<q", len(name)))
            f.write(name)
            # dtype by name ('bfloat16', 'float32', ...) — extension dtypes
            # have an opaque .str ('|V2') that can't round-trip
            dt = np_arr.dtype.name.encode()
            f.write(struct.pack("<q", len(dt)))
            f.write(dt)
            f.write(struct.pack("<q", np_arr.ndim))
            for d in np_arr.shape:
                f.write(struct.pack("<q", d))
            raw = np_arr.tobytes()
            f.write(struct.pack("<q", len(raw)))
            f.write(raw)


def load(fname):
    """Load NDArrays saved by :func:`save` or by reference MXNet's mx.nd.save."""
    with open(fname, "rb") as f:
        return _load_fileobj(f, fname)


def loads(buf):
    """Load NDArrays from raw bytes (the predict-API path: reference
    MXPredCreate takes the .params file CONTENT, c_predict_api.cc:44)."""
    import io

    return _load_fileobj(io.BytesIO(buf), "<bytes>")


def _load_fileobj(f, fname):
    magic = f.read(8)
    if magic == _SAVE_MAGIC:
        return _load_container_format(f)
    if len(magic) == 8 and struct.unpack("<Q", magic)[0] == _NDLIST_MAGIC:
        return _load_reference_format(f)
    raise MXNetError(
        "Invalid NDArray file format in %s: neither the MXNet binary "
        "NDArray-list format (magic 0x112) nor the MXTPU001 container" % fname)


def _load_reference_format(f):
    (_reserved,) = struct.unpack("<Q", f.read(8))
    (num,) = struct.unpack("<Q", f.read(8))
    arrays = []
    for _ in range(num):
        (first,) = struct.unpack("<I", f.read(4))
        if first == _NDARRAY_V1_MAGIC:
            (ndim,) = struct.unpack("<I", f.read(4))
            shape = struct.unpack("<%dq" % ndim, f.read(8 * ndim)) if ndim else ()
        else:
            # legacy TShape: `first` IS ndim, u32 dims (LegacyTShapeLoad)
            ndim = first
            shape = struct.unpack("<%dI" % ndim, f.read(4 * ndim)) if ndim else ()
        if ndim == 0:
            # reference: none-NDArray — no ctx/type/data bytes follow
            arrays.append(array(_np.zeros((0,), dtype=_np.float32)))
            continue
        _dev_type, _dev_id = struct.unpack("<ii", f.read(8))
        (type_flag,) = struct.unpack("<i", f.read(4))
        if type_flag not in _FLAG_TO_DTYPE:
            raise MXNetError("Unsupported dtype flag %d in NDArray file" % type_flag)
        dt = _np.dtype(_FLAG_TO_DTYPE[type_flag])
        count = int(_np.prod(shape))
        np_arr = _np.frombuffer(f.read(dt.itemsize * count), dtype=dt).reshape(shape)
        arrays.append(array(np_arr))
    (nkeys,) = struct.unpack("<Q", f.read(8))
    if nkeys == 0:
        return arrays
    if nkeys != num:
        # reference hard-fails here too (CHECK keys->size()==data->size(),
        # ndarray.cc:742-743) — silently dropping arrays would restore a
        # checkpoint with missing params
        raise MXNetError("Invalid NDArray file format: %d names for %d arrays"
                         % (nkeys, num))
    keys = []
    for _ in range(nkeys):
        (klen,) = struct.unpack("<Q", f.read(8))
        keys.append(f.read(klen).decode())
    return dict(zip(keys, arrays))


def _load_container_format(f):
    (num,) = struct.unpack("<q", f.read(8))
    (has_keys,) = struct.unpack("<q", f.read(8))
    keys, arrays = [], []
    for _ in range(num):
        (nlen,) = struct.unpack("<q", f.read(8))
        keys.append(f.read(nlen).decode())
        (dlen,) = struct.unpack("<q", f.read(8))
        dt_name = f.read(dlen).decode()
        try:
            dt = _np.dtype(dt_name)
        except TypeError:
            import ml_dtypes

            dt = _np.dtype(getattr(ml_dtypes, dt_name))
        (ndim,) = struct.unpack("<q", f.read(8))
        shape = tuple(struct.unpack("<q", f.read(8))[0] for _ in range(ndim))
        (rlen,) = struct.unpack("<q", f.read(8))
        np_arr = _np.frombuffer(f.read(rlen), dtype=dt).reshape(shape)
        arrays.append(array(np_arr))
    if has_keys:
        return dict(zip(keys, arrays))
    return arrays


# ----------------------------------------------------------------------
# generated op namespace (parity: reference codegen ndarray.py:2362-2514
# `_make_ndarray_function` — here generated from the registry at import)
# ----------------------------------------------------------------------


def _tracer_free(args):
    """False when any operand is (backed by) a live jax Tracer: a
    CustomOp / torch-bridge forward may run imperative ops INSIDE an
    active jax transformation, and deferring those to a worker thread
    would leak the tracer out of its trace
    (jax.errors.UnexpectedTracerError) — they must execute eagerly on
    the tracing thread."""
    for a in args:
        if isinstance(a, NDArray):
            base = a
            while base._parent is not None:
                base = base._parent
            if isinstance(base._data, jax.core.Tracer):
                return False
        elif isinstance(a, jax.core.Tracer):
            return False
    return True


def _engine_invoke(op, args, kwargs, ctx, priority=0):
    """Dispatch one single-output op through the dependency engine
    (reference Engine::PushAsync from MXImperativeInvoke,
    c_api_ndarray.cc:248-430): returns the output handle immediately;
    the value materializes on an engine worker once all input writers
    have completed.  Reads on the result synchronize via its chunk var.
    Tracer operands fall back to eager inline execution.

    Under lazy imperative evaluation (lazy.py; MXTPU_LAZY, on by
    default) the op is not executed at all: it joins the context's
    pending expression graph and the whole chain later runs as ONE
    jitted dispatch.  Deferral is skipped inside engine ops (the chain
    would escape the op's declared var footprint) and while the
    autograd tape records (the tape must observe program order)."""
    if not _tracer_free(args):
        return NDArray(op.fn(*[_as_jax(a) for a in args], **kwargs), ctx)
    # non-NDArray operands — positional AND keyword — are snapshotted
    # NOW: a numpy scratch buffer the caller mutates after this call has
    # no engine var, so only an eager copy (_snapshot) keeps the op's
    # inputs at their call-site values (jax.Arrays are immutable, so
    # they pass through untouched)
    args = tuple(a if isinstance(a, NDArray) else _snapshot(a)
                 for a in args)
    if kwargs and any(isinstance(v, _np.ndarray) for v in kwargs.values()):
        kwargs = {
            k: _snapshot(v) if isinstance(v, _np.ndarray) else v
            for k, v in kwargs.items()}
    if _RECORD_HOOK is not None:
        # autograd boundary: recorded ops must observe program order
        # against any pending fused chain, and are never deferred
        lazy.flush_all("sync")
    elif lazy.enabled() and not engine.in_engine_op():
        out = lazy.record(op, args, kwargs, ctx)
        if out is not None:
            return out
    out = NDArray(None, ctx)
    eng = engine.get()
    read_vars = [a._engine_var() for a in args if isinstance(a, NDArray)]

    def _run(_op=op, _args=args, _kw=kwargs, _out=out):
        from . import telemetry

        if telemetry.enabled():
            telemetry.inc("ndarray.imperative_dispatches")
        jax_args = [a._raw() if isinstance(a, NDArray) else a for a in _args]
        _out._set_data(_op.fn(*jax_args, **_kw))

    eng.push(_run, read_vars=read_vars, write_vars=(out._engine_var(),),
             priority=priority, name=op.name)
    return out


def _engine_dispatchable(op, args):
    """Ops the engine path covers: single fixed output, no aux-state
    mutation, no host RNG (draw order must follow program order), no
    mesh/is_train plumbing, and no variadic list arguments."""
    return (op.num_outputs == 1 and op.num_aux_out == 0
            and not op.need_rng and not op.need_mesh and not op.need_is_train
            and not any(isinstance(a, (list, tuple)) for a in args))


def _make_nd_function(op):
    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)  # accepted for symbol-compat call sites
        ctx = kwargs.pop("ctx", None)
        res_ctx = None
        for a in args:
            if isinstance(a, NDArray):
                res_ctx = a.ctx
                break
        res_ctx = ctx or res_ctx or current_context()
        if op.params:
            from .ops.params import validate_attrs

            validate_attrs(op, kwargs)
        if _engine_dispatchable(op, args):
            boxed = _engine_invoke(op, args, kwargs, res_ctx)
        else:
            jax_args = [_as_jax(a) for a in args]
            result = op.fn(*jax_args, **kwargs)
            if isinstance(result, tuple):
                main = result[: len(result) - op.num_aux_out] if op.num_aux_out else result
                boxed = tuple(NDArray(r, res_ctx) for r in main)
                if len(boxed) == 1:
                    boxed = boxed[0]
            else:
                boxed = NDArray(result, res_ctx)
        if _RECORD_HOOK is not None:
            nd_ins = [a for a in args if isinstance(a, NDArray)]
            nd_outs = list(boxed) if isinstance(boxed, tuple) else [boxed]
            # non-NDArray args are captured as constants in the replay
            # fn (snapshotted — the replay must see call-site values)
            spec = [None if isinstance(a, NDArray) else _snapshot(a)
                    for a in args]

            # mxlint: disable=W101 -- deliberate def-time snapshot: the replay closure must see the kwargs as they were at record time; the default is never mutated
            def _replay(*xs, _f=op.fn, _kw=dict(kwargs), _spec=spec):
                it = iter(xs)
                vals = [next(it) if s is None else s for s in _spec]
                return _f(*vals, **_kw)

            _RECORD_HOOK(_replay, nd_ins, nd_outs)
        if out is not None:
            if isinstance(boxed, tuple):
                for o, b in zip(out if isinstance(out, (list, tuple)) else [out], boxed):
                    o[:] = b
            else:
                out[:] = boxed
            return out
        return boxed

    fn.__name__ = op.name
    fn.__doc__ = op.doc
    return fn


def _populate(module):
    seen = {}
    for name, op in OP_REGISTRY.items():
        if id(op) not in seen:
            seen[id(op)] = _make_nd_function(op)
        public = name
        if not hasattr(module, public):
            setattr(module, public, seen[id(op)])


_populate(sys.modules[__name__])
