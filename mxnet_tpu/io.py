"""Data iterators (parity: reference python/mxnet/io.py + src/io/).

Host-side pipeline feeding the device: the reference's C++ chain
(record parser → BatchLoader → Normalize → PrefetcherIter double-buffering,
reference src/io/iter_prefetcher.h:28-130) maps to python iterators with a
background prefetch thread; the heavy RecordIO/image path has a native C++
backend (src/recordio.cc via recordio.py ctypes bindings).
"""
from __future__ import annotations

import gzip
import os
import struct
from collections import namedtuple

import numpy as _np

from .base import MXNetError
from . import ndarray as nd
from .engine.threaded_iter import ThreadedIter
from .ndarray import NDArray, array

__all__ = [
    "DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
    "PrefetchingIter", "DeviceStagedIter", "StagedBlock", "MNISTIter",
    "CSVIter", "ImageRecordIter", "ImageDetRecordIter",
    "ShardedImageRecordIter", "stage_put",
]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Data description incl. dtype/layout (parity: io.py DataDesc)."""

    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype, self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


def desc_shape(desc):
    """Shape of a bind-style shape spec: DataDesc or plain (name, shape)."""
    return tuple(desc.shape) if hasattr(desc, "shape") else tuple(desc[1])


def redesc(desc, shape):
    """A DataDesc like `desc` (DataDesc or (name, shape) tuple) with a
    new shape — dtype/layout carried over when present."""
    if hasattr(desc, "shape"):
        return DataDesc(desc.name, shape, desc.dtype, desc.layout)
    return DataDesc(desc[0], shape)


class DataBatch:
    """One batch: data/label NDArray lists + pad/index (parity: io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None, bucket_key=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Base iterator (parity: io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(), pad=self.getpad(), index=self.getindex()
            )
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        pass

    def getdata(self):
        pass

    def getlabel(self):
        pass

    def getindex(self):
        return None

    def getpad(self):
        pass


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (parity: io.py NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = _np.arange(self.data[0][1].shape[0])
        if shuffle:
            _np.random.shuffle(self.idx)
            self.data = [(k, v[self.idx]) for k, v in self.data]
            self.label = [(k, v[self.idx]) for k, v in self.label]
        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]
        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])), v.dtype)
            for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])), v.dtype)
            for k, v in self.label
        ]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(), pad=self.getpad(), index=None
            )
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [array(x[1][self.cursor : self.cursor + self.batch_size]) for x in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [
            array(_np.concatenate((x[1][self.cursor :], x[1][:pad]), axis=0)) for x in data_source
        ]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


def _init_data(data, allow_empty, default_name):
    """Normalize input data (parity: io.py _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them or dict with them as values")
    for k, v in data.items():
        if isinstance(v, NDArray):
            data[k] = v.asnumpy()
    return list(sorted(data.items()))


class ResizeIter(DataIter):
    """Resize another iterator to `size` batches per epoch (parity: io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Engine-backed prefetch over one or more iterators (parity: io.py
    PrefetchingIter; reference double-buffering iter_prefetcher.h:96-118
    over dmlc threadediter — here each batch fetch is one engine op, so
    decode overlaps with device compute on the engine's worker pool and
    `mx.waitall()` fences IO along with everything else)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self._bg_iters = None
        self.current_batch = [None for _ in range(self.n_iter)]
        self._start_prefetch()

    def _start_prefetch(self):
        self._bg_iters = [
            ThreadedIter(it.next, max_prefetch=2, name="prefetch_%d" % i)
            for i, it in enumerate(self.iters)
        ]

    def _stop_prefetch(self):
        """Stop background fetching and DRAIN it: after this returns no
        engine op is still calling into the wrapped iterators, so the
        caller may safely reset or destroy them.  Idempotent — reset()
        cycles and repeated close() calls must not double-release (or
        leak one fetch pipeline per epoch)."""
        if self._bg_iters is not None:
            for bg in self._bg_iters:
                bg.close()
        self._bg_iters = None

    def close(self):
        """Final teardown: drain this iterator's prefetch ops AND close the
        wrapped iterators (joining any worker threads they own, e.g.
        ImageRecordIter's decode pool).  Idempotent; the iterator is not
        usable afterwards (unlike reset(), which restarts prefetch)."""
        self._stop_prefetch()
        for it in self.iters:
            inner_close = getattr(it, "close", None)
            if callable(inner_close):
                inner_close()

    def __del__(self):
        if self._bg_iters is not None:
            for bg in self._bg_iters:
                bg.cancel()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum(
            [
                [DataDesc(r[x.name], x.shape, x.dtype) if isinstance(x, DataDesc) else DataDesc(r[x[0]], x[1])
                 for x in i.provide_data]
                for r, i in zip(self.rename_data, self.iters)
            ],
            [],
        )

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum(
            [
                [DataDesc(r[x.name], x.shape, x.dtype) if isinstance(x, DataDesc) else DataDesc(r[x[0]], x[1])
                 for x in i.provide_label]
                for r, i in zip(self.rename_label, self.iters)
            ],
            [],
        )

    def reset(self):
        self._stop_prefetch()
        for it in self.iters:
            it.reset()
        self._start_prefetch()

    def iter_next(self):
        batches = []
        for bg in self._bg_iters:
            try:
                batches.append(next(bg))
            except StopIteration:
                batches.append(None)
        if any(b is None for b in batches):
            return False
        self.current_batch = batches
        return True

    def next(self):
        if self.iter_next():
            if self.n_iter == 1:
                return self.current_batch[0]
            return DataBatch(
                data=sum([b.data for b in self.current_batch], []),
                label=sum([b.label for b in self.current_batch], []),
                pad=self.current_batch[0].pad,
                index=self.current_batch[0].index,
            )
        raise StopIteration

    def getdata(self):
        return sum([b.data for b in self.current_batch], [])

    def getlabel(self):
        return sum([b.label for b in self.current_batch], [])

    def getindex(self):
        return self.current_batch[0].index

    def getpad(self):
        return self.current_batch[0].pad


class StagedBlock:
    """K training batches stacked on a new leading axis, resident on
    device: the unit of work of the K-step fused dispatch
    (Executor.fused_update_block).

    * ``data`` / ``label`` — lists of (K, ...) device arrays aligned with
      ``provide_data`` / ``provide_label``;
    * ``label_host`` — per-step numpy labels ([[arr, ...] per step]) kept
      on the host so update_metric never reads the device block back;
    * ``count`` — number of real steps K (the last block of an epoch may
      be short);
    * ``pad`` — pad rows of the FINAL step (earlier steps are full).
    """

    __slots__ = ("data", "label", "label_host", "count", "pad",
                 "_mem_booked")

    def __init__(self, data, label, label_host, count, pad=0):
        self.data = data
        self.label = label
        self.label_host = label_host
        self.count = count
        self.pad = pad
        # live-buffer census: a staged block pins device memory from
        # H2D until the fused dispatch consumes (donates) it — book it
        # so "what is holding bytes right now" can name staging depth
        self._mem_booked = 0
        from . import telemetry

        if telemetry.enabled():
            from .obs import memory

            self._mem_booked = sum(
                int(getattr(a, "nbytes", 0) or 0)
                for a in list(self.data) + list(self.label))
            memory.book("staged_blocks", self._mem_booked)

    def __del__(self):
        try:
            booked, self._mem_booked = self._mem_booked, 0
            if booked:
                from .obs import memory

                memory.unbook("staged_blocks", booked)
        except Exception:  # pragma: no cover — interpreter teardown
            pass


def stage_put(name, arr, place_fn=None):
    """Count and place ONE stacked host input — the H2D half shared by
    the training staging pipeline (DeviceStagedIter blocks) and the
    serving continuous batcher (request batches, serving/session.py):
    the staged bytes land in the same `io.stage_bytes` /
    `io.stage_block_bytes` books either way, so "is the host feeding
    the device in big-enough transfers" has one answer across both
    pipelines.  `place_fn(name, arr)` does the actual device placement;
    None keeps the array host-side."""
    from . import telemetry

    if telemetry.enabled():
        telemetry.inc("io.stage_bytes", int(arr.nbytes))
        # size DISTRIBUTION too: whether transfers are big enough to
        # amortize the per-transfer overhead is a bucket question
        telemetry.observe("io.stage_block_bytes", int(arr.nbytes),
                          buckets=telemetry.BYTE_BUCKETS)
    return place_fn(name, arr) if place_fn is not None else arr


class DeviceStagedIter(DataIter):
    """Async device staging: groups K batches from `data_iter` into one
    stacked StagedBlock and `jax.device_put`s it from a BACKGROUND engine
    op, so the host decode + H2D of block N+1 overlap block N's device
    compute — the tf.data prefetch-to-device recipe layered on the
    reference's double-buffered PrefetcherIter (src/io/iter_prefetcher.h).

    The fetch rides engine.ThreadedIter (one engine op per block on the
    shared worker pool, its iterator var declared as the op's write set,
    so SanitizerEngine sees a fully-declared pipeline and `mx.waitall()`
    fences staging along with everything else).  ``MXTPU_STAGE_BUFFERS``
    blocks are kept in flight (default 2 = classic double buffering).
    Each staging op records an ``h2d_stage`` profiler span, so overlap
    with the ``fused_dispatch(K)`` lane is visible in the trace.

    `place_fn(name, stacked_array)` does the actual device placement —
    Module.fit passes Executor.place_block_input so blocks land with the
    executor's input sharding; without it blocks stay host-side and the
    executor places them at dispatch (no overlap, same results).
    """

    def __init__(self, data_iter, steps_per_dispatch=None, place_fn=None,
                 buffers=None):
        super().__init__()
        from . import config

        self._inner = data_iter
        k = (steps_per_dispatch if steps_per_dispatch is not None
             else config.get("MXTPU_STEPS_PER_DISPATCH"))
        self._k = max(1, int(k))
        self._place_fn = place_fn
        self._buffers = max(1, int(buffers if buffers is not None
                                   else config.get("MXTPU_STAGE_BUFFERS")))
        self.batch_size = getattr(data_iter, "batch_size", 0)
        self._bg = None
        self._start()

    @property
    def steps_per_dispatch(self):
        return self._k

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def _start(self):
        self._bg = ThreadedIter(self._fetch_block, max_prefetch=self._buffers,
                                name="h2d_stage")

    def _names(self, descs):
        return [d.name if isinstance(d, DataDesc) else d[0] for d in descs]

    def _fetch_block(self):
        """One staging op: pull up to K batches, stack host-side, device-
        put.  Runs on an engine worker while the consumer's previous
        block computes on device; the whole decode+stack+H2D is recorded
        as one `h2d_stage` profiler span."""
        import time as _time

        from . import profiler, telemetry

        t0 = _time.time()
        batches = []
        while len(batches) < self._k:
            try:
                batches.append(self._inner.next())
            except StopIteration:
                break
        if not batches:
            raise StopIteration
        block = self._assemble(batches)
        if profiler.spans_active():
            t1 = _time.time()
            profiler.record_span("h2d_stage", int(t0 * 1e6),
                                 int((t1 - t0) * 1e6), cat="io")
        if telemetry.enabled():
            telemetry.observe("io.h2d_stage_seconds", _time.time() - t0)
            telemetry.inc("io.blocks_staged")
        return block

    def _assemble(self, batches):
        from . import telemetry

        def host(a):
            if isinstance(a, NDArray):
                # a device-resident batch (e.g. NDArrayIter output) is
                # read BACK to host before stacking — a real D2H leg of
                # the staging path, counted so the transfer books
                # balance (numpy-producing iterators skip it)
                out = a.asnumpy()
                if telemetry.enabled():
                    telemetry.inc("executor.d2h_bytes", int(out.nbytes))
                return out
            return _np.asarray(a)

        def stack_put(names, rows):
            return [stage_put(name, _np.stack([host(b[i]) for b in rows]),
                              self._place_fn)
                    for i, name in enumerate(names)]

        data_names = self._names(self.provide_data)
        data = stack_put(data_names, [b.data for b in batches])
        label, label_host = [], None
        if batches[0].label:
            label_names = self._names(self.provide_label)
            label = stack_put(label_names, [b.label for b in batches])
            label_host = [[host(a) for a in b.label] for b in batches]
        return StagedBlock(data, label, label_host, len(batches),
                           pad=batches[-1].pad or 0)

    def next(self):
        if self._bg is None:
            raise MXNetError("DeviceStagedIter is closed (reset() restarts "
                             "a live iterator; a closed one is done)")
        return next(self._bg)

    def iter_next(self):
        raise NotImplementedError("DeviceStagedIter yields StagedBlocks; "
                                  "iterate with next()")

    def reset(self):
        """Drain in-flight staging ops, rewind the source, restart the
        lookahead.  Idempotent per cycle — no staging pipeline survives
        from the previous epoch."""
        self.close()
        self._inner.reset()
        self._start()

    def close(self):
        """Stop staging and drain outstanding ops (after this returns the
        source iterator is no longer being read, so the owner may reset
        or destroy it).  Idempotent.  Does NOT close the source — the
        training loop owns its lifetime."""
        if self._bg is not None:
            self._bg.close()
        self._bg = None

    def __del__(self):
        if getattr(self, "_bg", None) is not None:
            self._bg.cancel()


class MNISTIter(NDArrayIter):
    """MNIST raw-ubyte reader (parity: reference src/io/iter_mnist.cc:61-241).

    Reads idx-format image/label files (optionally .gz); `flat` controls
    (B,784) vs (B,1,28,28).
    """

    def __init__(self, image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
                 batch_size=128, shuffle=True, flat=False, silent=False, seed=0,
                 input_shape=None, **kwargs):
        images = _read_idx_images(image)
        labels = _read_idx_labels(label)
        images = images.astype(_np.float32) / 255.0
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1, images.shape[1], images.shape[2])
        super().__init__(
            images, labels.astype(_np.float32), batch_size=batch_size,
            shuffle=bool(shuffle), last_batch_handle="discard",
            data_name="data", label_name="softmax_label",
        )


def _open_maybe_gz(path):
    if not os.path.exists(path) and os.path.exists(path + ".gz"):
        path = path + ".gz"
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def _read_idx_images(path):
    with _open_maybe_gz(path) as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise MXNetError("Invalid MNIST image file %s" % path)
        data = _np.frombuffer(f.read(num * rows * cols), dtype=_np.uint8)
        return data.reshape(num, rows, cols)


def _read_idx_labels(path):
    with _open_maybe_gz(path) as f:
        magic, num = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise MXNetError("Invalid MNIST label file %s" % path)
        return _np.frombuffer(f.read(num), dtype=_np.uint8)


class CSVIter(NDArrayIter):
    """CSV reader (parity: reference src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=128, round_batch=True, **kwargs):
        data = _np.loadtxt(data_csv, delimiter=",", dtype=_np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=_np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = _np.zeros((data.shape[0],), dtype=_np.float32)
        super().__init__(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard",
        )


def ImageRecordIter(**kwargs):
    """RecordIO-packed image iterator (reference src/io/iter_image_recordio_2.cc).

    Implemented over the native C++ RecordIO reader — see image_io.py.
    """
    from .image_io import ImageRecordIterImpl

    return ImageRecordIterImpl(**kwargs)


def ImageDetRecordIter(**kwargs):
    """Detection record iterator with bbox-aware augmentation
    (reference src/io/iter_image_det_recordio.cc) — see det_io.py."""
    from .det_io import ImageDetRecordIterImpl

    return ImageDetRecordIterImpl(**kwargs)


def ShardedImageRecordIter(**kwargs):
    """Multi-process sharded RecordIO image iterator (mxnet_tpu.data):
    ``num_workers`` decode PROCESSES (default ``MXTPU_DATA_WORKERS``)
    feed batches through shared-memory rings, with deterministic
    ``(seed, epoch)`` coverage, per-host sharding composed on top
    (``host_index``/``num_hosts``), and worker-crash detection.  Same
    decode/augment surface as ``ImageRecordIter``; plugs into
    ``DeviceStagedIter``/``Module.fit`` like any DataIter.  See
    docs/data.md."""
    from .data.iter import ShardedImageRecordIter as _Impl

    return _Impl(**kwargs)
