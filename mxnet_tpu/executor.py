"""Executor — binds a Symbol to devices and runs it.

TPU-native equivalent of the reference GraphExecutor
(reference src/executor/graph_executor.cc, include/mxnet/executor.h).

Architecture mapping (SURVEY.md §7 phase 3):
  * The reference builds the full fwd+bwd graph, runs NNVM passes
    (PlanMemory, AttachOpExecs, DetectInplaceAddTo), then replays cached
    engine ops per node with bulk "segments".  Here the ENTIRE graph is
    lowered into ONE jitted XLA executable per (is_train, backward) mode —
    bulk-exec taken to its limit; XLA is the memory planner and fuser.
  * Gradient pass ≙ `jax.vjp` over the interpreted graph.  Loss ops carry
    `custom_vjp` so `backward()` without head gradients matches reference
    semantics (graph_executor.cc:102-175 AggregateGradient: multiple
    consumers of one variable sum naturally under AD).
  * grad_req 'write'/'add'/'null' (reference OpReqType) applied on the
    host side after the fused call; 'add' accumulates into grad arrays.
  * Multi-device: pass `mesh` — inputs are sharded over the mesh's 'data'
    axis, params replicated; XLA SPMD inserts the gradient all-reduce that
    the reference got from KVStore device-mode P2P reduction
    (src/kvstore/comm.h:204-355).  This is the TPU-idiomatic data path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import MXNetError
from .context import Context, current_context
from .ndarray import NDArray
from .symbol import _topo_order

__all__ = ["Executor"]

# monotonic retrace-monitor scope tokens: each binding's jit caches are
# judged independently (telemetry.note_retrace scope=), and a counter —
# unlike id(self) — can never alias a garbage-collected executor's
# identity onto a fresh one
import itertools as _itertools

_RETRACE_SCOPE_SEQ = _itertools.count()


def _run_graph(entries, order, arg_names, aux_names, arg_vals, aux_vals, is_train, rng,
               boundary=None, cast=None, mesh=None):
    """Interpret the graph as pure JAX ops (traced once under jit).

    `rng` is a jax PRNG key (or None); callers inside jit build it from a
    host seed so no device-side key chain is maintained between steps.
    `boundary` is (replicated NamedSharding, {id(node): ctx_group}) — when
    an edge crosses two ctx_groups a replicated sharding constraint is
    applied, the SPMD analog of the reference's _CrossDeviceCopy insertion
    at PlaceDevice boundaries (reference src/executor/graph_executor.cc:347-360).
    `cast` is (compute_dtype, keep_fp32_names): float args are cast to the
    compute dtype ON ENTRY to the executable (labels and other names in the
    keep set stay fp32) and outputs are cast back on exit.  Aux states stay
    in their STORAGE dtype end-to-end — ops cast them at point of use — so
    fp32 running statistics never round-trip through bf16.
    Because the cast sits inside the traced function, `jax.vjp` returns
    fp32 gradients for the fp32 master parameters automatically — the
    multi-precision training recipe (reference python/mxnet/optimizer.py
    multi-precision SGD) with XLA doing conv/matmul in bf16 on the MXU.
    Returns (outputs tuple, aux_updates tuple ordered like aux_names).
    """

    def _to_compute(name, v):
        if cast is None:
            return v
        cdt, keep = cast
        if name in keep or not jnp.issubdtype(v.dtype, jnp.floating):
            return v
        return v.astype(cdt)

    out_dtypes = {n: v.dtype for n, v in zip(aux_names, aux_vals)}
    arg_env = {n: _to_compute(n, v) for n, v in zip(arg_names, arg_vals)}
    # aux states (BatchNorm running stats) are NEVER cast to the compute
    # dtype: re-quantizing carried fp32 statistics through bf16 every step
    # degrades them — the reference multi-precision recipe (cuDNN BN) keeps
    # statistics fp32 under fp16 compute; ops cast at the point of use
    aux_env = dict(zip(aux_names, aux_vals))
    env = {}
    aux_updates = dict(aux_env)
    for i, node in enumerate(order):
        if node.op is None:
            if node.is_aux:
                env[id(node)] = (aux_env[node.name],)
            else:
                env[id(node)] = (arg_env[node.name],)
            continue
        op = node.op
        ins = [env[id(src)][idx] for src, idx in node.inputs]
        if boundary is not None:
            repl, groups = boundary
            my_group = groups.get(id(node))
            ins = [
                jax.lax.with_sharding_constraint(v, repl)
                if groups.get(id(src)) is not None and groups.get(id(src)) != my_group
                else v
                for v, (src, idx) in zip(ins, node.inputs)
            ]
        ins += [aux_updates[a.name] for a in node.aux_vars]
        kwargs = {k: v for k, v in node.attrs.items() if not k.startswith("__") and k != "ctx_group"}
        if op.need_is_train:
            kwargs["is_train"] = is_train
        if op.need_rng:
            kwargs["rng"] = jax.random.fold_in(rng, i) if rng is not None else None
        if getattr(op, "need_mesh", False):
            kwargs["mesh"] = mesh
        # named_scope stamps the node name into HLO op metadata (tf_op),
        # so XLA device traces attribute time per GRAPH NODE even though
        # the whole step is one fused executable — the analog of the
        # reference profiler's per-op SetOprStart/End rows
        # (src/engine/profiler.cc:134-190).  Trace-time only; free at run.
        with jax.named_scope(node.name):
            res = op.fn(*ins, **kwargs)
        if not isinstance(res, tuple):
            res = (res,)
        if op.num_aux_out:
            main = res[: len(res) - op.num_aux_out]
            for a, upd in zip(node.aux_vars, res[len(res) - op.num_aux_out:]):
                aux_updates[a.name] = upd
            res = main
        env[id(node)] = res
    outputs = tuple(env[id(nd)][ix] for nd, ix in entries)
    aux_out = tuple(aux_updates[n] for n in aux_names)
    if cast is not None:
        outputs = tuple(
            o.astype(jnp.float32) if jnp.issubdtype(o.dtype, jnp.floating) else o
            for o in outputs)
        aux_out = tuple(a.astype(out_dtypes[n]) for n, a in zip(aux_names, aux_out))
    return outputs, aux_out


# remat policy for memory mirroring: MXU results (matmul/conv) are the
# expensive-to-recompute outputs — save those, recompute everything else
# (BN affines, activations, adds) in the backward pass
def _MIRROR_POLICY(prim, *_, **__):
    return prim.name in ("dot_general", "conv_general_dilated")


# op → input slots whose values are indices, not magnitudes
_INDEX_ARG_SLOTS = {
    "Embedding": (0,), "take": (1,), "batch_take": (1,), "one_hot": (0,),
    "gather_nd": (1,), "scatter_nd": (1,), "pick": (1,),
    "SequenceLast": (1,), "SequenceMask": (1,), "SequenceReverse": (1,),
}


def _index_like_args(symbol):
    """Variable args whose values reach an index slot of any consumer op,
    traced TRANSITIVELY through intermediate ops (an index routed through
    e.g. `slice` before `take` must not round through bf16 either).  The
    closure over-approximates — a variable feeding both an index path and a
    magnitude path is kept fp32, trading a little speed for correctness."""
    keep = set()
    pending = []  # nodes whose producing subgraph feeds an index slot
    for node in _topo_order(symbol._entries):
        if node.op is None:
            continue
        slots = _INDEX_ARG_SLOTS.get(node.op.name)
        if not slots:
            continue
        for i in slots:
            if i < len(node.inputs):
                pending.append(node.inputs[i][0])
    seen = set()
    while pending:
        src = pending.pop()
        if id(src) in seen:
            continue
        seen.add(id(src))
        if src.op is None:
            if not src.is_aux:
                keep.add(src.name)
        else:
            pending.extend(s for s, _ in src.inputs)
    return keep


def _auto_spec(shape, mesh, axis="model"):
    """Pick a PartitionSpec sharding the largest dim divisible by the model
    axis (params of a ctx_group are sharded, not placed — the SPMD
    reinterpretation of reference PlaceDevice)."""
    from .parallel.mesh import P

    if axis not in mesh.axis_names:
        return P()
    m = mesh.shape[axis]
    dims = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in dims:
        if shape[d] % m == 0 and shape[d] >= m:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()


def _resolve_group2ctx(symbol, group2ctx, mesh):
    """Map ctx_group annotations to mesh shardings.

    Reference semantics (src/executor/graph_executor.cc:347-360): each
    ctx_group is PLACED on the device from `group2ctx` and _CrossDeviceCopy
    nodes move activations between groups.  Whole-array placement is an
    MPMD pattern XLA SPMD does not express (and an anti-pattern on TPU);
    the TPU-first translation is: build a 'model' mesh over the union of
    group devices, SHARD each group's parameters across it, and put a
    sharding constraint at group boundaries (the copy analog).  Memory per
    device drops the way placement would drop it; numerics are identical.

    Returns (mesh, param_shardings, node_groups); degrades to
    (mesh, {}, None) with a warning when <2 distinct devices are given.
    """
    import logging as _logging

    from .symbol import _topo_order as _topo

    order = _topo(symbol._entries)
    node_groups = {}
    for node in order:
        g = node.attrs.get("ctx_group") if node.attrs else None
        if g is not None:
            node_groups[id(node)] = g
    if not node_groups:
        _logging.warning("group2ctx given but symbol has no ctx_group annotations")
        return mesh, {}, None
    # param variables inherit the group of their first consumer op
    param_groups = {}
    for node in order:
        if node.op is None:
            continue
        g = node_groups.get(id(node))
        if g is None:
            continue
        for src, _ in node.inputs:
            if src.op is None and not src.is_aux and src.name not in param_groups:
                param_groups[src.name] = g
    devices = []
    for g, ctx in group2ctx.items():
        d = ctx.jax_device()
        if d not in devices:
            devices.append(d)
    if len(devices) < 2:
        _logging.warning(
            "group2ctx maps all groups onto one physical device; "
            "running without model sharding")
        return mesh, {}, None
    if mesh is not None and "model" in mesh.axis_names:
        model_mesh = mesh
    elif mesh is not None:
        # an existing mesh without a 'model' axis means the caller already
        # chose a layout (e.g. DP over contexts); don't silently replace it
        _logging.warning(
            "group2ctx ignored: executor mesh %s has no 'model' axis — pass "
            "a mesh like make_mesh({'data': -1, 'model': k}) to combine "
            "data and model parallelism" % (mesh.axis_names,))
        return mesh, {}, None
    else:
        import numpy as _np

        from .parallel.mesh import Mesh

        model_mesh = Mesh(_np.array(devices), ("model",))
    shardings = {n: "auto" for n in param_groups}
    return model_mesh, shardings, node_groups


class Executor:
    """Bound computation graph (parity: python/mxnet/executor.py Executor)."""

    def __init__(self, symbol, ctx, arg_dict, grad_dict, grad_req, aux_dict, mesh=None,
                 param_shardings=None, node_groups=None, compute_dtype=None,
                 fp32_names=(), mirror=None):
        self._symbol = symbol
        if mirror is None:
            from . import config

            mirror = bool(config.get("MXNET_BACKWARD_DO_MIRROR"))
        self._mirror = bool(mirror)
        self._compute_dtype = jnp.dtype(compute_dtype) if compute_dtype else None
        fp32 = set(fp32_names)
        if self._compute_dtype is not None:
            # args consumed as INDICES (token ids, gather positions) must
            # not round through bf16 — ids > 256 are not bf16-exact
            fp32 |= _index_like_args(symbol)
        self._fp32_names = frozenset(fp32)
        self._ctx = ctx
        self.arg_dict = arg_dict
        self.grad_dict = grad_dict
        self.aux_dict = aux_dict
        self._grad_req = grad_req
        self._mesh = mesh
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self._entries = symbol._entries
        self._order = _topo_order(self._entries)
        self._outputs_cache = None
        self._last_is_train = False
        self._monitor_callback = None
        from .ops.random_ops import HOST_RNG

        self._step_seed = int(HOST_RNG.randint(0, 2 ** 31))
        self._aux_applied = False
        self._jit_fwd = {}
        self._jit_bwd = {}
        # every compile-cache entry this executor built via the memory
        # plane (obs/memory.py Program) — released on predictor
        # eviction/close so the ProgramFootprint table cannot drift
        # upward across a long-lived serving process
        self._mem_programs = []
        # training-dispatch telemetry: how many device round-trips the
        # training loop has issued (fused single steps, K-step blocks,
        # and materialized fwd+bwd calls each count 1) — bench.py reports
        # dispatches = ceil(steps / steps_per_dispatch) from this
        self._train_dispatches = 0
        # >0 after a K-step block dispatch: outputs are stacked (K, ...)
        # and update_metric consumes the whole block; any plain forward
        # resets it
        self._last_block_count = 0
        self._data_sharding = None
        self._repl_sharding = None
        self._param_shardings = dict(param_shardings or {})
        self._node_groups = node_groups
        if mesh is not None:
            from .parallel.mesh import NamedSharding, P, batch_pspec

            # batch_pspec covers both a flat 'data' axis and the
            # hierarchical 'data_dcn' x 'data_ici' split of a multi-host
            # mesh (parallel/multihost.global_mesh hierarchical=True)
            self._data_sharding = NamedSharding(mesh, batch_pspec(mesh))
            self._repl_sharding = NamedSharding(mesh, P())
            # ops may declare per-input mesh axes (Op.input_axes, e.g. MoE
            # experts over 'expert'): shard those params dim-0 AT REST so
            # expert memory scales 1/E across the axis — the EP analog of
            # the reference's per-device expert placement
            for node in self._order:
                if node.op is None or not getattr(node.op, "input_axes", None):
                    continue
                for (src, _), in_name in zip(node.inputs, node.op.inputs):
                    ax = node.op.input_axes.get(in_name)
                    if (ax and ax in mesh.axis_names and src.op is None
                            and not src.is_aux
                            and src.name not in self._param_shardings):
                        self._param_shardings[src.name] = P(ax)

    # ------------------------------------------------------------------
    # construction (parity: Executor::SimpleBind / Bind)
    # ------------------------------------------------------------------
    @staticmethod
    def simple_bind(symbol, ctx=None, grad_req="write", type_dict=None, mesh=None,
                    shared_exec=None, group2ctx=None, param_shardings=None,
                    compute_dtype=None, fp32_names=(), mirror=None, **kwargs):
        """Allocate all arrays from shapes and bind
        (reference GraphExecutor simple_bind overload, executor.h:76)."""
        ctx = ctx or current_context()
        node_groups = None
        if group2ctx:
            mesh, auto_shardings, node_groups = _resolve_group2ctx(symbol, group2ctx, mesh)
            auto_shardings.update(param_shardings or {})
            param_shardings = auto_shardings
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**kwargs)
        if arg_shapes is None:
            raise MXNetError("simple_bind: cannot infer shapes from %s" % kwargs)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        type_dict = type_dict or {}
        if type_dict:
            # propagate the given dtypes through the graph so untyped params
            # are allocated in the inferred dtype (reference simple_bind
            # InferType, graph_executor.cc:793-806)
            arg_types, _, _ = symbol.infer_type(**type_dict)
            inferred = dict(zip(arg_names, arg_types))
            type_dict = {n: type_dict.get(n, inferred[n]) for n in arg_names}
        arg_dict, grad_dict = {}, {}
        req_dict = _norm_grad_req(grad_req, arg_names)
        shared = shared_exec.arg_dict if shared_exec is not None else {}
        shared_grad = shared_exec.grad_dict if shared_exec is not None else {}
        for name, shape in zip(arg_names, arg_shapes):
            dtype = jnp.dtype(type_dict.get(name, "float32"))
            if name in shared and tuple(shared[name].shape) == tuple(shape):
                arg_dict[name] = shared[name]
            else:
                arg_dict[name] = NDArray(jnp.zeros(shape, dtype=dtype), ctx)
            if req_dict.get(name, "null") != "null":
                if name in shared_grad and tuple(shared_grad[name].shape) == tuple(shape):
                    grad_dict[name] = shared_grad[name]
                else:
                    grad_dict[name] = NDArray(jnp.zeros(shape, dtype=dtype), ctx)
        shared_aux = shared_exec.aux_dict if shared_exec is not None else {}
        aux_dict = {}
        for name, shape in zip(aux_names, aux_shapes):
            if name in shared_aux and tuple(shared_aux[name].shape) == tuple(shape):
                aux_dict[name] = shared_aux[name]
            else:
                aux_dict[name] = NDArray(jnp.zeros(shape, dtype=jnp.float32), ctx)
        return Executor(symbol, ctx, arg_dict, grad_dict, req_dict, aux_dict, mesh=mesh,
                        param_shardings=param_shardings, node_groups=node_groups,
                        compute_dtype=compute_dtype, fp32_names=fp32_names,
                        mirror=mirror)

    @staticmethod
    def bind(symbol, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None, mesh=None, param_shardings=None,
             compute_dtype=None, fp32_names=(), mirror=None):
        """Bind with user-provided arrays (reference Executor::Bind).

        `group2ctx` maps ctx_group names to Contexts: groups are sharded
        over a 'model' mesh built from those devices (see _resolve_group2ctx
        for the SPMD translation of reference PlaceDevice)."""
        ctx = ctx or current_context()
        node_groups = None
        if group2ctx:
            mesh, auto_shardings, node_groups = _resolve_group2ctx(symbol, group2ctx, mesh)
            auto_shardings.update(param_shardings or {})
            param_shardings = auto_shardings
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        if isinstance(args, dict):
            arg_dict = {n: args[n] for n in arg_names if n in args}
            missing = [n for n in arg_names if n not in args]
            if missing:
                raise MXNetError("bind: missing arguments %s" % missing)
        else:
            if len(args) != len(arg_names):
                raise MXNetError("bind: expected %d args, got %d" % (len(arg_names), len(args)))
            arg_dict = dict(zip(arg_names, args))
        req_dict = _norm_grad_req(grad_req, arg_names)
        if args_grad is None:
            grad_dict = {}
            for n in arg_names:
                if req_dict.get(n, "null") != "null":
                    req_dict[n] = "null"
        elif isinstance(args_grad, dict):
            grad_dict = dict(args_grad)
            for n in arg_names:
                if n not in grad_dict:
                    req_dict[n] = "null"
        else:
            grad_dict = dict(zip(arg_names, args_grad))
        if aux_states is None:
            aux_dict = {n: NDArray(jnp.zeros(()), ctx) for n in aux_names} if aux_names else {}
            if aux_names:
                # infer aux shapes from args
                shapes = {n: arg_dict[n].shape for n in arg_names}
                _, _, aux_shapes = symbol.infer_shape(**shapes)
                aux_dict = {
                    n: NDArray(jnp.zeros(s), ctx) for n, s in zip(aux_names, aux_shapes)
                }
        elif isinstance(aux_states, dict):
            aux_dict = dict(aux_states)
        else:
            aux_dict = dict(zip(aux_names, aux_states))
        return Executor(symbol, ctx, arg_dict, grad_dict, req_dict, aux_dict, mesh=mesh,
                        param_shardings=param_shardings, node_groups=node_groups,
                        compute_dtype=compute_dtype, fp32_names=fp32_names,
                        mirror=mirror)

    # ------------------------------------------------------------------
    # data-path helpers
    # ------------------------------------------------------------------
    @property
    def _data_arg_names(self):
        # args without grads are inputs (data/label); used for sharding decisions
        return [n for n in self._arg_names if self._grad_req.get(n, "null") == "null"]

    def _gather_args(self):
        vals = []
        for n in self._arg_names:
            v = self.arg_dict[n].data
            vals.append(v)
        return tuple(vals)

    def _gather_aux(self):
        return tuple(self.aux_dict[n].data for n in self._aux_names)

    def _place(self, vals):
        """Apply mesh shardings: batch inputs over 'data', params per their
        sharding spec ('model'-axis TP / group2ctx shards) or replicated."""
        if self._mesh is None:
            return vals
        from .parallel.mesh import NamedSharding, global_put

        placed = []
        data_names = set(self._data_arg_names)
        for n, v in zip(self._arg_names, vals):
            if n in data_names:
                sh = self._data_sharding
            elif n in self._param_shardings:
                spec = self._param_shardings[n]
                if spec == "auto":
                    spec = _auto_spec(v.shape, self._mesh)
                sh = NamedSharding(self._mesh, spec)
            else:
                sh = self._repl_sharding
            # global_put = device_put that also materializes pjit/GDA-
            # style global arrays when the mesh spans other processes
            placed.append(global_put(v, sh))
        return tuple(placed)

    def _place_repl(self, vals):
        """Replicate aux/optimizer-state leaves over the mesh.  On a
        multi-process mesh this is REQUIRED: a committed process-local
        array cannot enter a global-mesh executable (the data/param args
        already flow through _place) — global_put materializes the
        pjit-style replicated global array from each host's copy."""
        if self._mesh is None:
            return tuple(vals)
        from .parallel.mesh import global_put

        return tuple(global_put(v, self._repl_sharding) for v in vals)

    def _boundary(self):
        """(replicated sharding, node→group) for cross-group constraints."""
        if self._node_groups and self._mesh is not None:
            return (self._repl_sharding, self._node_groups)
        return None

    def _cast(self):
        """(compute_dtype, keep-fp32 names) for mixed-precision, or None."""
        if self._compute_dtype is None:
            return None
        return (self._compute_dtype, self._fp32_names)

    # ------------------------------------------------------------------
    # forward / backward (parity: MXExecutorForward/Backward)
    # ------------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """Set inputs and (lazily) run forward.

        Training-mode forward DEFERS computation: if `backward()` follows
        (the fit hot path), one fused fwd+bwd executable runs exactly once —
        the analog of the reference's bulk-exec segments
        (graph_executor.cc:1094-1170).  Reading `outputs` before backward
        triggers a forward-only run with the SAME per-step RNG key, so
        dropout masks agree between reported outputs and gradients, and
        aux (BatchNorm moving stats) updates apply exactly once per step.
        """
        for name, value in kwargs.items():
            if name not in self.arg_dict:
                raise MXNetError("Unknown argument %s" % name)
            if isinstance(value, NDArray):
                v = value.data
            else:
                # raw numpy/list input converts (and transfers) here;
                # NDArray inputs paid their H2D at creation (nd.array).
                # Bytes counted AFTER conversion so list inputs (no
                # .nbytes) are measured exactly.
                host = not isinstance(value, jax.Array)
                v = jnp.asarray(value)
                if host:
                    self._note_bytes("executor.h2d_bytes", v.nbytes)
            if tuple(v.shape) != tuple(self.arg_dict[name].shape):
                raise MXNetError(
                    "Shape mismatch for argument %s: bound %s, got %s (use reshape())"
                    % (name, self.arg_dict[name].shape, tuple(v.shape))
                )
            self.arg_dict[name]._set_data(v)
        self._last_is_train = bool(is_train)
        self._last_block_count = 0
        # a fresh forward supersedes any staged-but-undispatched block:
        # without this, update() after a skipped block dispatch would
        # re-run the stale block instead of this batch's deferred step
        self._pending_fused_block = False
        self._staged_block = None
        self._outputs_cache = None
        self._next_seed()
        self._aux_applied = False
        if not is_train:
            self._compute_forward(False)
        return self.outputs if not is_train else None

    # ------------------------------------------------------------------
    # telemetry helpers (each early-returns when the registry is off,
    # so hot paths pay one predicted branch — the enabled() contract)
    # ------------------------------------------------------------------
    def _note_compile_cache(self, hit, site=None, signature=None):
        """One executable-cache lookup: a miss means an XLA (re)compile —
        steady-state training must show hits only (a miss churn here is
        the bucketing-rebind / shape-instability smell).  Misses that
        carry a `site`/`signature` also feed the retrace monitor
        (telemetry.note_retrace, the runtime half of mxlint W104):
        the second DISTINCT signature at one site counts a
        ``trace.retraces`` and, past MXTPU_RETRACE_WARN, logs the
        signature delta."""
        from . import telemetry

        if not telemetry.enabled():
            return
        telemetry.inc("executor.compile_cache_hits" if hit
                      else "executor.compile_cache_misses")
        if not hit and site is not None:
            scope = getattr(self, "_retrace_scope", None)
            if scope is None:
                scope = self._retrace_scope = next(_RETRACE_SCOPE_SEQ)
            telemetry.note_retrace(site, signature, scope=scope)

    def _mem_program(self, fn, site, signature, donate_argnums=()):
        """Build one compile-cache entry through the memory plane
        (obs/memory.py): an AOT-compiling wrapper that harvests XLA's
        compiled memory analysis into the ProgramFootprint table and
        catches RESOURCE_EXHAUSTED for the OOM postmortem.  Drop-in
        for ``jax.jit(fn, donate_argnums=...)`` — tracked per executor
        so eviction can release the footprints."""
        from .obs import memory

        p = memory.program(fn, site=site, key=signature,
                           donate_argnums=donate_argnums)
        self._mem_programs.append(p)
        return p

    def release_footprints(self, evicted=False):
        """Remove this executor's programs from the ProgramFootprint
        table (predict.py signature-cache eviction and Predictor.close
        call this); `evicted=True` additionally ticks the
        ``mem.programs_evicted`` counter — the census-drift satellite
        of the memory plane."""
        from . import telemetry

        programs, self._mem_programs = self._mem_programs, []
        for p in programs:
            p.release()
        if evicted and programs and telemetry.enabled():
            telemetry.inc("mem.programs_evicted", len(programs))

    def _note_dispatch(self, kind, elapsed):
        """One training dispatch: wall latency split by dispatch shape
        (`step` = single fused fwd+bwd(+update), `block` = K-step scan)."""
        from . import telemetry

        if not telemetry.enabled():
            return
        telemetry.inc("executor.train_dispatches")
        telemetry.observe("executor.dispatch_seconds.%s" % kind, elapsed)

    def _note_bytes(self, name, nbytes):
        from . import telemetry

        if not telemetry.enabled():
            return
        telemetry.inc(name, int(nbytes))

    def flops_per_step(self, is_train=True):
        """Analytic FLOPs of one step of the bound symbol (fwd traced via
        jax.make_jaxpr — pure tracing, no device work; training steps
        count fwd+bwd as 3x forward, the standard accounting).  Cached;
        0.0 when the trace fails.  telemetry's per-step MFU gauge is
        this over measured step time and tools/tpu_constants.py peak."""
        cache = getattr(self, "_flops_cache", None)
        if cache is None:
            cache = self._flops_cache = {}
        if is_train not in cache:
            from . import telemetry

            try:
                import numpy as _np

                # the UNJITTED forward closure: tracing it must not seed
                # _jit_fwd, or the first real forward would be counted
                # as a compile-cache hit while XLA still compiles it
                jaxpr = jax.make_jaxpr(self._build_fwd(is_train))(
                    self._gather_args(), self._gather_aux(), _np.uint32(0))
                fwd = telemetry.flops_of_jaxpr(jaxpr)
                cache[is_train] = fwd * (3.0 if is_train else 1.0)
            except Exception:
                cache[is_train] = 0.0
        return cache[is_train]

    def _build_fwd(self, is_train):
        """The raw (unjitted) forward closure — jitted+cached by _fwd_fn;
        flops_per_step traces it directly."""
        entries, order = self._entries, self._order
        an, xn = self._arg_names, self._aux_names
        boundary = self._boundary()
        cast = self._cast()

        mesh = self._mesh

        def f(arg_vals, aux_vals, seed):
            rng = jax.random.key(seed)
            return _run_graph(entries, order, an, xn, arg_vals, aux_vals, is_train,
                              rng, boundary=boundary, cast=cast, mesh=mesh)

        return f

    def _fwd_fn(self, is_train):
        if is_train not in self._jit_fwd:
            self._jit_fwd[is_train] = self._mem_program(
                self._build_fwd(is_train), "executor.forward", is_train)
        return self._jit_fwd[is_train]

    def _next_seed(self):
        # host-side step seed: device-side key splitting costs an RTT per
        # step on tunneled TPUs; the key is derived from this seed INSIDE
        # the jitted executable
        from .ops.random_ops import HOST_RNG

        self._step_seed = int(HOST_RNG.randint(0, 2 ** 31))
        return self._step_seed

    def _compute_forward(self, is_train):
        from . import profiler

        compiled = is_train in self._jit_fwd
        self._note_compile_cache(compiled, site="executor.forward",
                                 signature=is_train)
        fn = self._fwd_fn(is_train)
        args = self._place(self._gather_args())
        import numpy as _np

        with profiler.span("forward(is_train=%s)%s"
                           % (is_train, "" if compiled else " +compile"),
                           cat="executor"):
            outs, aux_upd = fn(args, self._place_repl(self._gather_aux()),
                               _np.uint32(self._step_seed))
        self._outputs_cache = [NDArray(o, self._first_ctx) for o in outs]
        if is_train and not self._aux_applied:
            self._write_aux(aux_upd)
            self._aux_applied = True
        if self._monitor_callback is not None:
            for name, o in zip(self._symbol.list_outputs(), self._outputs_cache):
                self._monitor_callback(name, o)

    @property
    def _first_ctx(self):
        return self._ctx if isinstance(self._ctx, Context) else self._ctx[0]

    def _write_aux(self, aux_upd):
        for n, v in zip(self._aux_names, aux_upd):
            self.aux_dict[n]._set_data(v)

    @property
    def outputs(self):
        if self._outputs_cache is None:
            self._compute_forward(self._last_is_train)
        return self._outputs_cache

    # ------------------------------------------------------------------
    # serving dispatch: a forward-only program whose batch inputs are a
    # separate (donated) leading argument — the continuous batcher
    # (serving/) stages a padded request batch to device and calls this
    # directly, so no NDArray arg_dict mutation sits on the hot path and
    # the staged input buffer is recycled by XLA the moment the fill's
    # compute consumes it (the "ping-pong donated buffer" half of the
    # serving pipeline; docs/serving.md)
    # ------------------------------------------------------------------
    def serve_program(self, input_names):
        """Jitted inference program `fn(input_vals, other_vals, aux_vals,
        seed) -> outputs` with `input_names` gathered into the donated
        leading tuple and every remaining argument (params, dead label
        args) in `other_vals`.  Cached in the executor's jit cache under
        the input-name signature, so a (tenant, bucket) program compiles
        ONCE and every later fill is a cache hit (counted in
        executor.compile_cache_hits/_misses like the training paths)."""
        names = tuple(input_names)
        key = ("serve", names)
        self._note_compile_cache(key in self._jit_fwd,
                                 site="executor.serve", signature=names)
        if key not in self._jit_fwd:
            an = self._arg_names
            missing = [n for n in names if n not in an]
            if missing:
                raise MXNetError("serve_program: unknown inputs %s" % missing)
            in_idx = [an.index(n) for n in names]
            other_idx = [i for i in range(len(an)) if i not in set(in_idx)]
            entries, order, xn = self._entries, self._order, self._aux_names
            boundary, cast, mesh = self._boundary(), self._cast(), self._mesh

            def f(input_vals, other_vals, aux_vals, seed):
                vals = [None] * len(an)
                for i, v in zip(in_idx, input_vals):
                    vals[i] = v
                for i, v in zip(other_idx, other_vals):
                    vals[i] = v
                rng = jax.random.key(seed)
                outs, _aux = _run_graph(entries, order, an, xn, tuple(vals),
                                        aux_vals, False, rng,
                                        boundary=boundary, cast=cast,
                                        mesh=mesh)
                return outs

            # donation is a TPU/GPU memory optimization; XLA:CPU does not
            # implement it and would warn on every dispatch — gate on
            # THIS executor's device, not the process default backend
            # (a host-side predictor may serve beside a TPU trainer)
            platform = self._first_ctx.jax_device().platform
            donate = (0,) if platform != "cpu" else ()
            self._jit_fwd[key] = self._mem_program(
                f, "executor.serve", names, donate_argnums=donate)
        return self._jit_fwd[key]

    def serve_args(self, input_names):
        """(other_vals, aux_vals) companions for :meth:`serve_program` —
        parameter/aux device refs gathered at dispatch time (cheap, and
        picks up params written between fills)."""
        names = set(input_names)
        other = tuple(self.arg_dict[n].data for n in self._arg_names
                      if n not in names)
        return other, self._gather_aux()

    # ------------------------------------------------------------------
    # single-dispatch training step (fwd + bwd + optimizer update in ONE
    # XLA executable with donated param/state buffers — the reference's
    # bulk-exec + update_on_kvstore taken to its limit)
    # ------------------------------------------------------------------
    def _grad_fwd(self, diff_idx, nondiff_idx):
        """Forward closure `fwd(dv, nondiff_vals, aux_vals, rng)` used by the
        gradient core; when mirroring is armed it is wrapped in
        `jax.checkpoint` so only matmul/conv outputs are kept as residuals."""
        entries, order = self._entries, self._order
        an, xn = self._arg_names, self._aux_names
        boundary = self._boundary()
        cast = self._cast()
        mesh = self._mesh

        def fwd(dv, nondiff_vals, aux_vals, rng):
            vals = [None] * len(an)
            for i, v in zip(diff_idx, dv):
                vals[i] = v
            for i, v in zip(nondiff_idx, nondiff_vals):
                vals[i] = v
            return _run_graph(entries, order, an, xn, tuple(vals), aux_vals,
                              True, rng, boundary=boundary, cast=cast, mesh=mesh)

        if self._mirror:
            fwd = jax.checkpoint(fwd, policy=_MIRROR_POLICY)
        return fwd

    def backward_residual_bytes(self):
        """Bytes of forward activations saved for the backward pass — the
        quantity memory mirroring shrinks (reference graph_executor.cc
        mirror pass reduces exactly this set).  Backend-independent: reads
        JAX's AD residuals rather than XLA buffer assignment."""
        from jax._src.ad_checkpoint import saved_residuals

        an = self._arg_names
        diff_idx = [i for i, n in enumerate(an)
                    if self._grad_req.get(n, "null") != "null"]
        nondiff_idx = [i for i in range(len(an)) if i not in set(diff_idx)]
        fwd = self._grad_fwd(diff_idx, nondiff_idx)
        all_vals = self._gather_args()
        dv = tuple(all_vals[i] for i in diff_idx)
        ndv = tuple(all_vals[i] for i in nondiff_idx)
        res = saved_residuals(fwd, dv, ndv, self._gather_aux(),
                              jax.random.key(0))
        total = 0
        for aval, _ in res:
            if hasattr(aval, "shape") and hasattr(aval, "dtype"):
                n = 1
                for d in aval.shape:
                    n *= int(d)
                total += n * jnp.dtype(aval.dtype).itemsize
        return total

    def _grad_core(self, diff_idx, nondiff_idx):
        """Build the shared fwd+vjp core used by both backward() and the
        fused step — ONE place owns the vals scatter and aux cotangents.

        Memory mirroring (reference graph_executor.cc:225-239
        MXNET_BACKWARD_DO_MIRROR): when armed, the forward is wrapped in
        `jax.checkpoint` with a policy that saves ONLY matmul/conv outputs
        — BN, activations, and other cheap elementwise results are
        recomputed during the backward pass instead of living in HBM
        across it.  Same trade as the reference (a few % more FLOPs for a
        large cut in peak activation memory), expressed as a remat policy
        instead of graph surgery."""
        fwd4 = self._grad_fwd(diff_idx, nondiff_idx)

        def core(diff_vals, nondiff_vals, aux_vals, rng, head_grads):
            def fwd(dv):
                return fwd4(dv, nondiff_vals, aux_vals, rng)

            (outs, aux_upd), vjp_fn = jax.vjp(fwd, diff_vals)
            if head_grads is None:
                cots = tuple(jnp.ones_like(o) for o in outs)
            else:
                cots = tuple(head_grads)
            zero_aux = tuple(jnp.zeros_like(a) for a in aux_upd)
            (grads,) = vjp_fn((cots, zero_aux))
            return outs, aux_upd, grads

        return core

    def install_fused_update(self, updater, index_of_name):
        """Arm the fused-dispatch training paths.  After this, `backward()`
        with no head grads defers and `fused_update()` runs fwd+bwd+update
        in one jitted call; `stage_block()` + `fused_update_block()` run
        K steps per dispatch (each dispatch sized from its staged block,
        so a short epoch tail just runs a smaller scan).  `index_of_name`
        maps arg name -> optimizer key."""
        self._fused_updater = updater
        self._fused_index_of_name = dict(index_of_name)
        self._jit_step = None
        self._jit_block = {}
        self._pending_fused = False
        self._pending_fused_block = False
        self._staged_block = None
        # step-invariant structure, computed once (grad_req/args fixed at bind)
        an = self._arg_names
        diff_names = [n for n in an if self._grad_req.get(n, "null") != "null"]
        diff_idx = [an.index(n) for n in diff_names]
        self._fused_static = (
            diff_names,
            diff_idx,
            [i for i in range(len(an)) if i not in set(diff_idx)],
        )

    def _ensure_fused_states(self, diff_names):
        """Create any missing per-key optimizer state (host side); returns
        {name: state leaves} for the armed updater."""
        from .optimizer import _state_leaves

        updater = self._fused_updater
        opt = updater.optimizer
        leaves_by_name = {}
        for n in diff_names:
            key = self._fused_index_of_name[n]
            if key not in updater.states:
                updater.states[key] = opt.create_state(key, self.arg_dict[n])
            leaves_by_name[n] = _state_leaves(updater.states[key])
        return leaves_by_name

    def fused_update(self):
        """Run the armed single-dispatch training step (see install_fused_update)."""
        import numpy as _np

        from .optimizer import schedule_prefix

        updater = self._fused_updater
        opt = updater.optimizer
        diff_names, diff_idx, nondiff_idx = self._fused_static
        leaves_by_name = self._ensure_fused_states(diff_names)
        scalars = schedule_prefix(
            opt, [self._fused_index_of_name[n] for n in diff_names], 1)[0]
        sig = tuple((n, tuple(l.shape for l in leaves_by_name[n])) for n in diff_names)
        first_call = self._jit_step is None or self._jit_step[1] != sig
        self._note_compile_cache(not first_call,
                                 site="executor.fused_step", signature=sig)
        if first_call:
            core = self._grad_core(diff_idx, nondiff_idx)

            def step(diff_vals, nondiff_vals, aux_vals, state_tuples, seed, scalars_arr):
                rng = jax.random.key(seed)
                outs, aux_upd, grads = core(diff_vals, nondiff_vals, aux_vals, rng, None)
                new_params, new_states = [], []
                for i, (w, g, st) in enumerate(zip(diff_vals, grads, state_tuples)):
                    nw, nst = opt._fused(w, g, st, scalars_arr[i, 0], scalars_arr[i, 1],
                                         scalars_arr[i, 2])
                    new_params.append(nw)
                    new_states.append(nst)
                return outs, aux_upd, tuple(new_params), tuple(new_states)

            jitted = self._mem_program(step, "executor.fused_step", sig,
                                       donate_argnums=(0, 3))
            self._jit_step = (jitted, sig)
        fn = self._jit_step[0]
        all_vals = self._place(self._gather_args())
        diff_vals = tuple(all_vals[i] for i in diff_idx)
        nondiff_vals = tuple(all_vals[i] for i in nondiff_idx)
        state_tuples = tuple(self._place_repl(
            tuple(l.data for l in leaves_by_name[n])) for n in diff_names)
        import time as _time

        from . import profiler, telemetry
        from .obs import recorder

        tel = telemetry.enabled()
        if tel:
            donated = (sum(v.nbytes for v in diff_vals)
                       + sum(l.nbytes for st in state_tuples for l in st))
            self._note_bytes("executor.donated_bytes", donated)
            # donated-buffer retirement rides the memory plane's books
            # too: XLA recycles these the moment the step consumes them
            self._note_bytes("mem.donated_retired_bytes", donated)
        # flight-recorder edge events (obs/recorder.py): the dispatch
        # bracket is what the stall watchdog watches, and the compile
        # bracket suppresses it across a legitimate first XLA compile
        rec = recorder.enabled()
        seq = self._train_dispatches + 1
        if rec:
            if first_call:
                recorder.record("compile", "enter", seq, detail="step")
            recorder.record("dispatch", "enter", seq, detail="step")
        t0 = _time.time() if tel else 0.0
        try:
            with profiler.span("fused_step(fwd+bwd+update)", cat="executor"):
                outs, aux_upd, new_params, new_states = fn(
                    diff_vals, nondiff_vals, self._place_repl(self._gather_aux()),
                    state_tuples, _np.uint32(self._step_seed), scalars,
                )
        finally:
            if rec:
                if first_call:
                    recorder.record("compile", "exit", seq)
                recorder.record("dispatch", "exit", seq)
        if tel:
            self._note_dispatch("step", _time.time() - t0)
        self._train_dispatches += 1
        self._outputs_cache = [NDArray(o, self._first_ctx) for o in outs]
        if not self._aux_applied:
            self._write_aux(aux_upd)
            self._aux_applied = True
        self._pending_fused = False
        for n, nw, nst in zip(diff_names, new_params, new_states):
            self.arg_dict[n]._set_data(nw)
            for l, v in zip(leaves_by_name[n], nst):
                l._set_data(v)

    # ------------------------------------------------------------------
    # K-step fused block: ONE dispatch = K full fwd+bwd+update steps.
    # A jitted lax.scan carries (params, optimizer state, aux) with
    # donated buffers over a stacked block of K batches — the reference's
    # bulk-exec (MXNET_EXEC_BULK_EXEC_TRAIN) extended ACROSS steps, so
    # the fixed per-dispatch cost (~11 ms tunnel overhead per chained
    # dispatch, bench.py) is paid once per K steps instead of once per
    # step.  Inputs arrive pre-staged (io.DeviceStagedIter overlaps the
    # H2D of block N+1 with block N's compute); scheduler scalars ride a
    # host-computed (K, n, 3) prefix (optimizer.schedule_prefix) so no
    # per-step scalar transfer remains.
    # ------------------------------------------------------------------
    def block_input_sharding(self):
        """Sharding for stacked (K, batch, ...) input blocks: the batch
        axis moves to position 1, so the 'data' mesh axis shards dim 1
        (None on single-device executors)."""
        if self._mesh is None:
            return None
        from .parallel.mesh import NamedSharding, batch_pspec

        return NamedSharding(self._mesh, batch_pspec(self._mesh, lead_dims=1))

    def place_block_input(self, name, arr):
        """Device-put one stacked input block with the right sharding —
        the H2D half of the staging pipeline; io.DeviceStagedIter calls
        this from a background engine op so the transfer overlaps device
        compute.  Idempotent: re-putting an already-placed block is a
        no-op, so the dispatch path can call it again safely."""
        if not isinstance(arr, jax.Array):
            # count H2D bytes only for HOST arrays: the dispatch path
            # re-places already-staged device blocks (the idempotent
            # no-op), which must not double the byte counter
            self._note_bytes("executor.h2d_bytes", arr.nbytes)
        sh = self.block_input_sharding()
        if sh is None:
            return jax.device_put(arr, self._first_ctx.jax_device())
        from .parallel.mesh import global_put

        return global_put(arr, sh)

    def stage_block(self, named_arrays, count):
        """Stage a stacked block of `count` batches for the next
        `fused_update_block()`.  `named_arrays` maps input arg name ->
        (count, ...) array (host or already device-put)."""
        unknown = [n for n in named_arrays if n not in self.arg_dict]
        if unknown:
            raise MXNetError("stage_block: unknown arguments %s" % unknown)
        self._staged_block = (dict(named_arrays), int(count))
        self._pending_fused_block = True
        # the staged block supersedes any deferred single step (mirror of
        # forward() clearing stale block state): without this, a later
        # update() could replay the abandoned step on stale inputs
        self._pending_fused = False
        self._outputs_cache = None
        self._aux_applied = False

    def _comm_mode(self):
        """(psum_axes, bucket_bytes) when EXPLICIT bucketed hierarchical
        gradient collectives are armed for the K-step block dispatch,
        else None (the implicit path: XLA's SPMD partitioner inserts the
        gradient all-reduce itself).

        Armed by MXTPU_COMM_BUCKETED=1 — or automatically ('auto') on a
        multi-process mesh, where controlling the collective layout is
        the point: grads pack into MXTPU_COMM_BUCKET_MB buckets, each
        reduced ICI-first then DCN (collectives.hierarchical_psum), and
        each bucket's all-reduce depends only on its member grads so it
        overlaps the rest of the backward structurally.  Only the pure
        data-parallel regime qualifies: TP/EP param shardings, ctx_group
        boundaries, mesh-needing ops, and batch-/valid-normalized losses
        keep the implicit partitioner path (their collectives/shape
        reads are the partitioner's job).

        SEMANTICS NOTE: train-mode BatchNorm computes batch statistics
        per SHARD on this path (the reference's per-device BN) while
        the implicit partitioner computes global-batch statistics
        (SyncBN-like); moving stats are pmean'd across shards each
        step.  Valid data-parallel training either way, but not
        bit-parity between the two modes for BN models — fine-tune
        flows wanting exact parity use fit(frozen_bn=True)
        (docs/distributed.md).

        Cached per executor (like _fused_static): the answer is constant
        for a bound graph, and this sits on the per-dispatch and
        per-epoch host paths — toggling MXTPU_COMM_* mid-process takes
        effect on the next bind."""
        cached = getattr(self, "_comm_mode_cache", "unset")
        if cached != "unset":
            return cached
        self._comm_mode_cache = self._comm_mode_impl()
        return self._comm_mode_cache

    def _comm_mode_impl(self):
        if self._mesh is None:
            return None
        from .parallel.mesh import data_axes

        axes = data_axes(self._mesh)
        if not axes or set(axes) != set(self._mesh.axis_names):
            return None
        size = 1
        for a in axes:
            size *= self._mesh.shape[a]
        if size <= 1:
            return None
        if self._node_groups or self._param_shardings:
            return None
        for node in self._order:
            if node.op is None:
                continue
            if getattr(node.op, "need_mesh", False) \
                    or getattr(node.op, "input_axes", None):
                return None
            # batch-/valid-normalized losses divide the gradient by a
            # PER-SHARD count inside shard_map (ops/nn.py _softmax_bwd
            # reads data.shape[0], which is local there) — psumming
            # those local means would over-scale grads n_shards x.  The
            # implicit partitioner sees the GLOBAL shape and stays
            # correct, so such graphs keep it.
            if node.attrs and str(node.attrs.get(
                    "normalization", "null")) != "null":
                return None
        # every output must carry the batch on dim 0: a batch-REDUCED
        # output (e.g. a Group'd mx.sym.sum head) has sum semantics the
        # per-shard pmean cannot reproduce — those graphs keep the
        # implicit partitioner, which reduces over the global array
        flags = self._out_batch_flags()
        if flags is None or not all(flags):
            return None
        from . import config

        mode = str(config.get("MXTPU_COMM_BUCKETED")).strip().lower()
        if mode in ("0", "off", "false", "no"):
            return None
        if mode in ("auto", "") and jax.process_count() <= 1:
            return None
        raw = config.get("MXTPU_COMM_BUCKET_MB")
        self._comm_bucket_auto = (raw == "auto")
        if self._comm_bucket_auto:
            # 'auto': arm with the registered default until the first
            # comm-mode block derives the real target from a measured
            # probe (autotune_comm_bucket) and re-arms this cache
            bucket_bytes = getattr(self, "_comm_auto_bytes", None) or max(
                1, int(float(
                    config.spec("MXTPU_COMM_BUCKET_MB").default) * 1e6))
        else:
            bucket_bytes = max(1, int(float(raw) * 1e6))
        # ICI-first reduction order: the innermost data axis is the LAST
        # in mesh order ('data_dcn' x 'data_ici' -> reduce ici, then dcn)
        return tuple(reversed(axes)), bucket_bytes

    def _out_batch_flags(self):
        """Per-output flag: does the leading dim carry the batch (so a
        comm-mode shard_map must tile it over the data axes) vs a
        reduced/replicated output (pmean'd across shards).  Cached: the
        full-graph infer_shape walk must not run per dispatch (arg
        shapes are fixed at bind; reshape builds a fresh executor)."""
        cached = getattr(self, "_out_batch_cache", "unset")
        if cached != "unset":
            return cached
        shapes = {n: tuple(self.arg_dict[n].shape) for n in self._arg_names}
        data_names = self._data_arg_names
        batch = shapes[data_names[0]][0] if data_names and \
            shapes[data_names[0]] else 0
        try:
            _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        except Exception:
            out_shapes = None
        if not out_shapes:
            self._out_batch_cache = None
        else:
            self._out_batch_cache = [bool(s) and batch > 0
                                     and s[0] == batch for s in out_shapes]
        return self._out_batch_cache

    def _build_block_fn(self, stream_idx, static_idx, comm,
                        out_batch=None):
        """The K-step scan over full fwd+bwd+update steps.  With `comm`
        armed the returned fn is written for a PER-SHARD view (wrapped in
        shard_map by the caller): the vjp gradients are local sums, so
        they are packed into size-targeted buckets and hierarchical-
        psum'd (ICI-first) right where backward produces them — inside
        the scan body, so the overlap with remaining backward compute is
        part of the HLO dependency structure; aux (BN stats) and
        non-batch outputs are pmean'd back to replicated."""
        an = self._arg_names
        diff_names, diff_idx, nondiff_idx = self._fused_static
        opt = self._fused_updater.optimizer
        core = self._grad_core(diff_idx, nondiff_idx)
        stream_pos = {i: p for p, i in enumerate(stream_idx)}
        static_pos = {i: p for p, i in enumerate(static_idx)}
        if comm is not None:
            from .parallel.collectives import (bucketed_psum,
                                               hierarchical_pmean)

            axes, bucket_bytes = comm

        def block(diff_vals, static_vals, aux_vals, state_tuples,
                  stream_vals, seeds_arr, scalars_arr):
            def body(carry, xs):
                dv, sts, aux = carry
                stream, seed, scal = xs
                nondiff = tuple(
                    stream[stream_pos[i]] if i in stream_pos
                    else static_vals[static_pos[i]]
                    for i in nondiff_idx)
                rng = jax.random.key(seed)
                outs, aux_upd, grads = core(dv, nondiff, aux, rng, None)
                if comm is not None:
                    grads, _ = bucketed_psum(grads, axes, bucket_bytes)
                    aux_upd = tuple(hierarchical_pmean(a, axes)
                                    for a in aux_upd)
                    if out_batch is not None:
                        outs = tuple(
                            o if is_b else hierarchical_pmean(o, axes)
                            for o, is_b in zip(outs, out_batch))
                new_params, new_states = [], []
                for j, (w, g, st) in enumerate(zip(dv, grads, sts)):
                    nw, nst = opt._fused(w, g, st, scal[j, 0],
                                         scal[j, 1], scal[j, 2])
                    new_params.append(nw)
                    new_states.append(nst)
                return ((tuple(new_params), tuple(new_states), aux_upd),
                        outs)

            carry, outs = jax.lax.scan(
                body, (diff_vals, state_tuples, aux_vals),
                (stream_vals, seeds_arr, scalars_arr))
            new_dv, new_sts, aux_out = carry
            return outs, aux_out, new_dv, new_sts

        return block

    def _wrap_comm_block(self, fn, out_batch):
        """shard_map the block over the mesh: params/state/aux/seeds
        replicated, stacked inputs sharded over the data axes on dim 1,
        batch-carrying outputs tiled back, everything else replicated
        (provably so — grads ride psum, stats ride pmean)."""
        from .parallel.collectives import shard_map_unchecked
        from .parallel.mesh import P, batch_pspec

        bspec = batch_pspec(self._mesh, lead_dims=1)
        out_spec_outs = tuple(bspec if b else P() for b in out_batch)
        return shard_map_unchecked(
            fn, mesh=self._mesh,
            in_specs=(P(), P(), P(), P(), bspec, P(), P()),
            out_specs=(out_spec_outs, P(), P(), P()))

    def _comm_plan_bytes(self, comm):
        """Host-side mirror of the bucket plan bucketed_psum will trace:
        per-bucket byte sizes for the armed diff params (telemetry +
        the comm probe's algorithmic-byte accounting).  Cached per
        bucket size — param shapes are fixed at bind, and this runs in
        the per-dispatch telemetry block."""
        cache = getattr(self, "_comm_plan_cache", None)
        if cache is None:
            cache = self._comm_plan_cache = {}
        if comm[1] not in cache:
            from .parallel.collectives import bucket_plan

            diff_names, _, _ = self._fused_static
            avals = [self.arg_dict[n].data for n in diff_names]
            cache[comm[1]] = [nb for _, nb in bucket_plan(avals, comm[1])]
        return cache[comm[1]]

    def fused_update_block(self):
        """Run the staged K-step block: one jitted lax.scan dispatch
        executing K full fwd+bwd+update steps (see stage_block).  On a
        comm-mode mesh (_comm_mode) the gradient sync inside the scan is
        explicit: bucketed, hierarchical (ICI-first), and overlapped
        with backward by construction — docs/distributed.md."""
        import numpy as _np

        from .optimizer import schedule_prefix

        named, k = self._staged_block
        updater = self._fused_updater
        opt = updater.optimizer
        an = self._arg_names
        diff_names, diff_idx, nondiff_idx = self._fused_static
        leaves_by_name = self._ensure_fused_states(diff_names)
        # host-computed scheduler prefix for the whole block — zero
        # per-step scalar RTTs (optimizer.py schedule_prefix)
        scalars = schedule_prefix(
            opt, [self._fused_index_of_name[n] for n in diff_names], k)
        # one host seed per step, drawn in the same order the single-step
        # path draws them (forward() -> _next_seed per step), so dropout
        # masks agree between steps_per_dispatch=K and K single dispatches
        seeds = _np.array([self._next_seed() for _ in range(k)],
                          dtype=_np.uint32)
        # streamed args (one slice per scan step) vs step-invariant args
        stream_idx = [i for i in nondiff_idx if an[i] in named]
        static_idx = [i for i in nondiff_idx if an[i] not in named]
        sig = tuple((n, tuple(l.shape for l in leaves_by_name[n]))
                    for n in diff_names)
        comm = self._comm_mode()
        if comm is not None and getattr(self, "_comm_bucket_auto", False) \
                and not getattr(self, "_comm_auto_done", False):
            # MXTPU_COMM_BUCKET_MB=auto: derive the real target from a
            # measured probe BEFORE the first block compiles, so the
            # first program already carries the tuned bucket plan (a
            # COLLECTIVE step — every rank reaches it at its first
            # comm-mode block)
            self.autotune_comm_bucket()
            comm = self._comm_mode()
        out_batch = None
        if comm is not None:
            # resolved ONCE and shared by the body and the shard_map
            # out_specs — the two must never disagree.  The comm gate
            # already required all-batch inferable outputs, so this is
            # the cached list, never None
            out_batch = self._out_batch_flags()
            assert out_batch is not None and all(out_batch),                 "comm mode armed without all-batch outputs (gate bug)"
        key = (k, tuple(an[i] for i in stream_idx), sig, comm)
        first_call = key not in self._jit_block
        self._note_compile_cache(not first_call,
                                 site="executor.fused_block", signature=key)
        if first_call:
            fn = self._build_block_fn(stream_idx, static_idx, comm,
                                      out_batch=out_batch)
            if comm is not None:
                fn = self._wrap_comm_block(fn, out_batch)
            self._jit_block[key] = self._mem_program(
                fn, "executor.fused_block", key, donate_argnums=(0, 3))
        self._last_block_key = key
        self._last_block_streams = (tuple(stream_idx), tuple(static_idx))
        fn = self._jit_block[key]
        all_vals = self._place(self._gather_args())
        diff_vals = tuple(all_vals[i] for i in diff_idx)
        static_vals = tuple(all_vals[i] for i in static_idx)
        stream_vals = tuple(self.place_block_input(an[i], named[an[i]])
                            for i in stream_idx)
        state_tuples = tuple(self._place_repl(
            tuple(l.data for l in leaves_by_name[n])) for n in diff_names)
        import time as _time

        from . import profiler, telemetry
        from .obs import recorder

        tel = telemetry.enabled()
        if tel:
            donated = (sum(v.nbytes for v in diff_vals)
                       + sum(l.nbytes for st in state_tuples for l in st))
            self._note_bytes("executor.donated_bytes", donated)
            self._note_bytes("mem.donated_retired_bytes", donated)
            if comm is not None:
                # bucket accounting is host-static (shapes + the plan
                # bucketed_psum traces): bytes_reduced counts one full
                # gradient sweep per scan step
                plan = self._comm_plan_bytes(comm)
                telemetry.inc("comm.dispatches")
                telemetry.inc("comm.bytes_reduced", sum(plan) * k)
                telemetry.set_gauge("comm.buckets", len(plan))
                for nb in plan:
                    telemetry.observe("comm.bucket_bytes", nb,
                                      buckets=telemetry.BYTE_BUCKETS)
        # flight-recorder bracket (obs/recorder.py): seq is the dispatch
        # counter, detail carries K and the comm bucket layout, bytes are
        # the per-sweep reduced gradient bytes — the post-mortem's "which
        # collective seq was in flight" answer.  The compile bracket
        # suppresses the stall watchdog across a first XLA compile.
        rec = recorder.enabled()
        seq = self._train_dispatches + 1
        if rec:
            if comm is not None:
                plan = self._comm_plan_bytes(comm)
                detail = "block(K=%d,buckets=%d)" % (k, len(plan))
                rec_bytes = sum(plan) * k
            else:
                detail, rec_bytes = "block(K=%d)" % k, 0
            if first_call:
                recorder.record("compile", "enter", seq, detail=detail)
            recorder.record("dispatch", "enter", seq, detail=detail,
                            nbytes=rec_bytes)
        t0 = _time.time() if tel else 0.0
        try:
            with profiler.span("fused_dispatch(K=%d)" % k, cat="executor"):
                outs, aux_upd, new_params, new_states = fn(
                    diff_vals, static_vals, self._place_repl(self._gather_aux()),
                    state_tuples, stream_vals, seeds, scalars)
        finally:
            if rec:
                if first_call:
                    recorder.record("compile", "exit", seq)
                recorder.record("dispatch", "exit", seq)
        if tel:
            self._note_dispatch("block", _time.time() - t0)
        self._train_dispatches += 1
        self._last_block_count = k
        # outputs arrive stacked (K, ...): ONE per-dispatch host readback
        # replaces K per-step ones (update_metric consumes the block)
        self._outputs_cache = [NDArray(o, self._first_ctx) for o in outs]
        self._write_aux(aux_upd)
        self._aux_applied = True
        self._pending_fused_block = False
        self._staged_block = None
        for n, nw, nst in zip(diff_names, new_params, new_states):
            self.arg_dict[n]._set_data(nw)
            for l, v in zip(leaves_by_name[n], nst):
                l._set_data(v)

    def _time_comm_only(self, axes, bucket_bytes, iters=2):
        """Compile and time ONE bucketed hierarchical gradient sweep at
        an arbitrary bucket size — zeros gradients on throwaway
        buffers, params untouched.  The shared probe under
        measure_comm's comm-only leg and autotune_comm_bucket's
        two-point model fit.  Returns mean seconds per sweep."""
        import time as _time

        import numpy as _np

        from . import profiler
        from .parallel.collectives import bucketed_psum, shard_map_unchecked
        from .parallel.mesh import P, global_put

        diff_names, _, _ = self._fused_static
        n_buckets = len(self._comm_plan_bytes((tuple(axes), bucket_bytes)))

        def comm_only(gs):
            red, _ = bucketed_psum(gs, axes, bucket_bytes)
            return red

        comm_fn = jax.jit(shard_map_unchecked(
            comm_only, mesh=self._mesh, in_specs=(P(),), out_specs=P()))
        gz = tuple(global_put(
            _np.zeros(self.arg_dict[nm].shape,
                      _np.dtype(self.arg_dict[nm].dtype)),
            self._repl_sharding) for nm in diff_names)
        jax.block_until_ready(comm_fn(gz))  # compile
        with profiler.span("comm_allreduce(buckets=%d)" % n_buckets,
                           cat="comm"):
            t0 = _time.time()
            for _ in range(iters):
                jax.block_until_ready(comm_fn(gz))
            return (_time.time() - t0) / iters

    def autotune_comm_bucket(self, iters=2):
        """MXTPU_COMM_BUCKET_MB=auto: derive the bucket target at fit
        start from a MEASURED probe (docs/perf.md "Autotuning").

        Times one full gradient sweep at the armed bucket size and at a
        quarter of it, fits the per-collective fixed cost c0 and the
        wire rate to the two points (tune.fit_comm_model), and adopts
        the smallest bucket whose per-sweep fixed-cost share stays
        under 10% of wire time (tune.derive_comm_bucket; clamped
        [1, 64] MB, no-flapping keep-threshold 25%).  On a
        multi-process mesh the derived target is allgathered and
        AVERAGED so every rank arms the IDENTICAL bucket plan —
        divergent plans would desync the collective schedule — and a
        rank whose probe did not fit the model vetoes the change
        everywhere.  The decision and its measured basis are booked as
        tune.* telemetry and a flight-recorder tune bracket; on a
        change the comm cache re-arms so the NEXT block program
        compiles with the target (prior variants stay jit-cached).

        A COLLECTIVE call — fused_update_block runs it at the first
        comm-mode block when armed, every rank in step.  Returns the
        decision record (also kept as _comm_auto_decision)."""
        import numpy as _np

        from . import telemetry, tune
        from .obs import recorder

        self._comm_auto_done = True
        comm = self._comm_mode()
        if comm is None:
            return None
        axes, cur_bytes = comm
        rec = recorder.enabled()
        if rec:
            recorder.record("tune", "enter", detail="comm_bucket(auto)")
        try:
            plan_cur = self._comm_plan_bytes((axes, cur_bytes))
            probe_bytes = max(1, cur_bytes // 4)
            plan_probe = self._comm_plan_bytes((axes, probe_bytes))
            t_cur = self._time_comm_only(axes, cur_bytes, iters=iters)
            t_probe = self._time_comm_only(axes, probe_bytes, iters=iters)
            n_dev = 1
            for a in axes:
                n_dev *= self._mesh.shape[a]
            sweep_bytes = sum(plan_cur)
            algo_bytes = 2.0 * (n_dev - 1) / n_dev * sweep_bytes
            proposal = tune.derive_comm_bucket(
                cur_bytes=cur_bytes, t_cur=t_cur, n_cur=len(plan_cur),
                t_probe=t_probe, n_probe=len(plan_probe),
                algo_bytes=algo_bytes, sweep_bytes=sweep_bytes)
            target = float(proposal["target_bytes"]) if proposal else 0.0
            if jax.process_count() > 1:
                # consensus: one rank's no-fit (target 0) vetoes the
                # change for everyone; otherwise the mean target arms
                from jax.experimental import multihost_utils

                gathered = _np.asarray(multihost_utils.process_allgather(
                    _np.float64(target))).reshape(-1)
                target = (0.0 if (gathered <= 0).any()
                          else float(gathered.mean()))
            decision = {
                "mode": "auto",
                "prev_bytes": int(cur_bytes),
                "applied_bytes": (int(target) if target > 0
                                  else int(cur_bytes)),
                "changed": bool(target > 0),
                "probe": {
                    "t_cur_s": t_cur, "buckets_cur": len(plan_cur),
                    "t_probe_s": t_probe,
                    "buckets_probe": len(plan_probe),
                    "probe_bytes": int(probe_bytes),
                    "sweep_bytes": int(sweep_bytes),
                    "algo_bytes": int(algo_bytes),
                },
                "model": (None if proposal is None else
                          {"c0_us": proposal["c0_s"] * 1e6,
                           "wire_gbps": proposal["wire_bps"] / 1e9}),
            }
            if target > 0:
                self._comm_auto_bytes = int(target)
                self._comm_mode_cache = "unset"  # re-arm with the target
            self._comm_auto_decision = decision
            if telemetry.enabled():
                telemetry.inc("tune.decisions")
                telemetry.inc("tune.comm_bucket_changed"
                              if decision["changed"]
                              else "tune.comm_bucket_kept")
                telemetry.set_gauge("tune.comm_bucket_bytes",
                                    decision["applied_bytes"])
                if proposal is not None:
                    telemetry.set_gauge("tune.comm_c0_us",
                                        proposal["c0_s"] * 1e6)
                    telemetry.set_gauge("tune.comm_wire_gbps",
                                        proposal["wire_bps"] / 1e9)
            return decision
        finally:
            if rec:
                recorder.record("tune", "exit",
                                detail="comm_bucket(auto)")

    def measure_comm(self, iters=3):
        """Measure the armed bucketed collectives against the compute
        they hide under — the three-program probe (docs/distributed.md):

          * comm-only — one bucketed hierarchical gradient sweep alone
            -> measured collective GB/s (ring-algorithm bytes / time),
          * compute-only — the SAME shard-mapped K-step block with the
            psums elided -> t_nocomm,
          * full — the real comm-mode block -> t_full.

        ``overlap_frac = (t_nocomm + K*t_comm + - t_full) / (K*t_comm)``
        clamped to [0, 1]: the fraction of collective time hidden under
        backward compute.  Records comm.gbps / comm.overlap_frac gauges
        (chrome counter lanes while profiling) plus comm_allreduce /
        comm_overlap_probe spans beside fused_dispatch(K).

        A COLLECTIVE probe: on a multi-process mesh every process must
        call it at the same point (bench.py --spmd-procs does).  Runs on
        throwaway copies — params/optimizer state are not advanced.
        Requires a prior comm-mode fused_update_block (the probe reuses
        its shapes)."""
        import time as _time

        import numpy as _np

        from . import profiler, telemetry
        from .optimizer import schedule_prefix
        from .parallel.mesh import global_put

        comm = self._comm_mode()
        key = getattr(self, "_last_block_key", None)
        if comm is None or key is None or key[3] != comm:
            raise MXNetError(
                "measure_comm: run at least one comm-mode K-step block "
                "dispatch first (fit on a >1-device data mesh with "
                "MXTPU_COMM_BUCKETED armed)")
        k = key[0]
        stream_idx, static_idx = self._last_block_streams
        axes, bucket_bytes = comm
        plan = self._comm_plan_bytes(comm)
        n = 1
        for a in axes:
            n *= self._mesh.shape[a]
        diff_names, diff_idx, nondiff_idx = self._fused_static
        leaves_by_name = self._ensure_fused_states(diff_names)
        an = self._arg_names

        def _fence(x):
            jax.block_until_ready(x)

        with profiler.span("comm_overlap_probe", cat="comm"):
            # -- comm-only: one bucketed hierarchical sweep ------------
            t_comm = self._time_comm_only(axes, bucket_bytes, iters=iters)
            # -- compute-only vs full block on throwaway inputs --------
            zeros_stream = tuple(global_put(
                _np.zeros((k,) + tuple(self.arg_dict[an[i]].shape),
                          _np.dtype(self.arg_dict[an[i]].dtype)),
                self.block_input_sharding()) for i in stream_idx)
            all_vals = self._place(self._gather_args())
            diff_vals = tuple(all_vals[i] for i in diff_idx)
            static_vals = tuple(all_vals[i] for i in static_idx)
            aux_vals = self._place_repl(self._gather_aux())
            state_tuples = tuple(self._place_repl(
                tuple(l.data for l in leaves_by_name[nm]))
                for nm in diff_names)
            seeds = _np.zeros((k,), _np.uint32)
            # schedule_prefix ADVANCES the optimizer's update counts (by
            # design, for real blocks) — the probe must leave the LR
            # schedule exactly where it found it
            opt_probe = self._fused_updater.optimizer
            saved_counts = (opt_probe.num_update,
                            dict(opt_probe._index_update_count))
            scalars = schedule_prefix(
                opt_probe,
                [self._fused_index_of_name[nm] for nm in diff_names], k)
            opt_probe.num_update = saved_counts[0]
            opt_probe._index_update_count = saved_counts[1]

            def timed(fn):
                outs = fn(diff_vals, static_vals, aux_vals, state_tuples,
                          zeros_stream, seeds, scalars)
                _fence(outs)  # compile + settle
                t0 = _time.time()
                for _ in range(iters):
                    _fence(fn(diff_vals, static_vals, aux_vals,
                              state_tuples, zeros_stream, seeds, scalars))
                return (_time.time() - t0) / iters

            # probe programs are built WITHOUT donation: the live param/
            # state buffers must survive.  Both variants share one
            # out_batch resolution with the real block
            out_batch = self._out_batch_flags()
            if out_batch is None:
                raise MXNetError("measure_comm: cannot infer output "
                                 "shapes for the bound symbol")
            t_full = timed(jax.jit(self._wrap_comm_block(
                self._build_block_fn(stream_idx, static_idx, comm,
                                     out_batch=out_batch), out_batch)))
            t_nocomm = timed(jax.jit(self._wrap_comm_block(
                self._build_block_fn(stream_idx, static_idx, None,
                                     out_batch=out_batch), out_batch)))
        sweep_bytes = sum(plan)
        algo_bytes = 2.0 * (n - 1) / n * sweep_bytes
        gbps = algo_bytes / t_comm / 1e9 if t_comm > 0 else 0.0
        overlap = 0.0
        if t_comm > 0:
            overlap = (t_nocomm + k * t_comm - t_full) / (k * t_comm)
            overlap = max(0.0, min(1.0, overlap))
        if telemetry.enabled():
            telemetry.set_gauge("comm.gbps", gbps)
            telemetry.set_gauge("comm.overlap_frac", overlap)
        return {"buckets": len(plan), "bucket_bytes": plan,
                "sweep_bytes": sweep_bytes, "devices": n,
                "t_comm_s": t_comm, "t_nocomm_s": t_nocomm,
                "t_full_s": t_full, "comm_gbps": gbps,
                "overlap_frac": overlap}

    def backward(self, out_grads=None):
        """Fused forward+backward in one XLA executable; grads land per grad_req.

        When a fused update is installed (see install_fused_update) and no
        head gradients are given, backward defers — update() completes the
        whole step in one dispatch.  grad_dict is NOT materialized on that
        path (gradients live only inside the fused executable)."""
        if getattr(self, "_fused_updater", None) is not None and out_grads is None:
            self._pending_fused = True
            return
        diff_names = [n for n in self._arg_names if self._grad_req.get(n, "null") != "null"]
        if not diff_names:
            return
        has_heads = out_grads is not None
        key = (True, has_heads)
        self._note_compile_cache(key in self._jit_bwd,
                                 site="executor.backward", signature=key)
        if key not in self._jit_bwd:
            an = self._arg_names
            diff_idx = [an.index(n) for n in diff_names]
            nondiff_idx = [i for i in range(len(an)) if i not in diff_idx]
            core = self._grad_core(diff_idx, nondiff_idx)

            def f(diff_vals, nondiff_vals, aux_vals, seed, head_grads):
                rng = jax.random.key(seed)
                return core(diff_vals, nondiff_vals, aux_vals, rng, head_grads)

            self._jit_bwd[key] = (
                self._mem_program(f, "executor.backward", key),
                diff_names, diff_idx, nondiff_idx)
        fn, diff_names, diff_idx, nondiff_idx = self._jit_bwd[key]
        all_vals = self._place(self._gather_args())
        diff_vals = tuple(all_vals[i] for i in diff_idx)
        nondiff_vals = tuple(all_vals[i] for i in nondiff_idx)
        heads = None
        if out_grads is not None:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = tuple(g.data if isinstance(g, NDArray) else jnp.asarray(g) for g in out_grads)
        import numpy as _np
        import time as _time

        from . import profiler, telemetry

        tel = telemetry.enabled()
        t0 = _time.time() if tel else 0.0
        with profiler.span("forward_backward", cat="executor"):
            outs, aux_upd, grads = fn(diff_vals, nondiff_vals,
                                      self._place_repl(self._gather_aux()),
                                      _np.uint32(self._step_seed), heads)
        if tel:
            self._note_dispatch("step", _time.time() - t0)
        self._train_dispatches += 1
        self._outputs_cache = [NDArray(o, self._first_ctx) for o in outs]
        if not self._aux_applied:
            self._write_aux(aux_upd)
            self._aux_applied = True
        for n, g in zip(diff_names, grads):
            req = self._grad_req.get(n, "write")
            tgt = self.grad_dict.get(n)
            if tgt is None:
                continue
            if req == "add":
                tgt._set_data(tgt.data + g)
            else:
                tgt._set_data(g)

    def forward_backward(self, out_grads=None, **kwargs):
        self.forward(is_train=True, **kwargs)
        self.backward(out_grads)
        return self.outputs

    # ------------------------------------------------------------------
    # misc (parity: python/mxnet/executor.py)
    # ------------------------------------------------------------------
    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._aux_names]

    def copy_params_from(self, arg_params, aux_params=None, allow_extra_params=False):
        for name, arr in (arg_params or {}).items():
            if name in self.arg_dict:
                self.arg_dict[name][:] = arr
            elif not allow_extra_params:
                raise MXNetError("Unknown param %s" % name)
        for name, arr in (aux_params or {}).items():
            if name in self.aux_dict:
                self.aux_dict[name][:] = arr
            elif not allow_extra_params:
                raise MXNetError("Unknown aux %s" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new input shapes, sharing parameter arrays
        (parity: executor.py reshape; reference shared-pool rebinding)."""
        new_shapes = dict(kwargs)
        arg_shapes, _, _ = self._symbol.infer_shape(**new_shapes)
        arg_dict = {}
        for n, s in zip(self._arg_names, arg_shapes):
            cur = self.arg_dict[n]
            if tuple(cur.shape) == tuple(s):
                arg_dict[n] = cur
            else:
                arg_dict[n] = NDArray(jnp.zeros(s, dtype=cur.dtype), self._first_ctx)
        new_exec = Executor(
            self._symbol, self._ctx, arg_dict,
            {n: NDArray(jnp.zeros_like(arg_dict[n].data), self._first_ctx) for n in self.grad_dict},
            dict(self._grad_req), dict(self.aux_dict), mesh=self._mesh,
            param_shardings=self._param_shardings, node_groups=self._node_groups,
            compute_dtype=self._compute_dtype, fp32_names=self._fp32_names,
            mirror=self._mirror,
        )
        # a rebound executor keeps the training regime: the fused
        # single-dispatch step survives reshape (bucketing hot path)
        if getattr(self, "_fused_updater", None) is not None:
            new_exec.install_fused_update(self._fused_updater,
                                          self._fused_index_of_name)
        return new_exec

    def set_monitor_callback(self, callback):
        self._monitor_callback = callback
        if callback is not None and getattr(self, "_fused_updater", None) is not None:
            # monitors need materialized outputs/grads — the single-dispatch
            # step keeps gradients inside the executable, so disarm it
            import logging
            logging.info(
                "Monitor installed: leaving the fused fwd+bwd+update "
                "dispatch (gradients must be materialized); expect lower "
                "step throughput while monitoring")
            self._fused_updater = None

    def debug_str(self):
        lines = ["Symbol outputs: %s" % self._symbol.list_outputs()]
        for node in self._order:
            if node.op is not None:
                lines.append("%s(%s) <- %s" % (node.op.name, node.name,
                                               [s.name for s, _ in node.inputs]))
        return "\n".join(lines)


def _norm_grad_req(grad_req, arg_names):
    if isinstance(grad_req, str):
        return {n: grad_req for n in arg_names}
    if isinstance(grad_req, (list, tuple)):
        return dict(zip(arg_names, grad_req))
    out = {n: "null" for n in arg_names}
    out.update(grad_req)
    return out
