/*
 * mxnet_tpu.hpp — a single-header C++ binding over the complete C ABI
 * (include/mxnet_tpu/c_api.h).
 *
 * Parity target: the reference cpp-package (/root/reference/cpp-package,
 * include/mxnet-cpp) and its core idiom — the GENERIC Operator class:
 *
 *     auto fc = Operator("FullyConnected")
 *                   .SetParam("num_hidden", 64)
 *                   .SetInput("data", x)
 *                   .CreateSymbol("fc1");
 *
 * No per-op code generation is needed: operators are addressed by name
 * and validated by the op registry behind the C ABI; the introspection
 * surface (MXSymbolListAtomicSymbolCreators / GetAtomicSymbolInfo) is
 * available for binding generators that DO want to emit typed wrappers
 * (see ListOperators / OperatorInfo below — the proof that a
 * third-party binding can enumerate the full op surface).
 *
 * Exceptions: every failing C call throws MXException carrying
 * MXGetLastError().  Handles are RAII-owned.
 *
 * Build: link against libmxnet_tpu.so —
 *     g++ -std=c++17 app.cc -I include -I cpp_package/include \
 *         -L <libdir> -lmxnet_tpu -Wl,-rpath,<libdir>
 */
#ifndef MXNET_TPU_CPP_HPP_
#define MXNET_TPU_CPP_HPP_

#include <mxnet_tpu/c_api.h>

#include <cstring>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace mxtpu {

class MXException : public std::runtime_error {
 public:
  explicit MXException(const std::string &where)
      : std::runtime_error(where + ": " + MXGetLastError()) {}
};

inline void Check(int rc, const char *where) {
  if (rc != 0) throw MXException(where);
}

/* --------------------------------------------------------- Context */
struct Context {
  int dev_type;  // 1 = cpu, 2 = accelerator (TPU)
  int dev_id;
  static Context cpu(int id = 0) { return {1, id}; }
  static Context tpu(int id = 0) { return {2, id}; }
};

/* --------------------------------------------------------- NDArray */
class NDArray {
 public:
  NDArray() = default;
  explicit NDArray(NDArrayHandle h) : h_(wrap(h)) {}
  NDArray(const std::vector<mx_uint> &shape, Context ctx = Context::cpu(),
          int dtype = 0) {
    NDArrayHandle h = nullptr;
    Check(MXNDArrayCreateEx(shape.data(),
                            static_cast<mx_uint>(shape.size()),
                            ctx.dev_type, ctx.dev_id, 0, dtype, &h),
          "NDArrayCreate");
    h_ = wrap(h);
  }
  NDArray(const std::vector<float> &data, const std::vector<mx_uint> &shape,
          Context ctx = Context::cpu())
      : NDArray(shape, ctx) {
    SyncCopyFromCPU(data);
  }

  NDArrayHandle handle() const { return h_.get(); }
  bool is_none() const { return !h_; }

  void SyncCopyFromCPU(const std::vector<float> &data) {
    Check(MXNDArraySyncCopyFromCPU(h_.get(), data.data(), data.size()),
          "SyncCopyFromCPU");
  }
  std::vector<float> SyncCopyToCPU() const {
    std::vector<float> out(Size());
    Check(MXNDArraySyncCopyToCPU(h_.get(), out.data(), out.size()),
          "SyncCopyToCPU");
    return out;
  }
  std::vector<mx_uint> Shape() const {
    mx_uint ndim = 0;
    const mx_uint *dims = nullptr;
    Check(MXNDArrayGetShape(h_.get(), &ndim, &dims), "GetShape");
    return std::vector<mx_uint>(dims, dims + ndim);
  }
  size_t Size() const {
    size_t n = 1;
    for (mx_uint d : Shape()) n *= d;
    return n;
  }
  void WaitToRead() const {
    Check(MXNDArrayWaitToRead(h_.get()), "WaitToRead");
  }
  NDArray Copy() const {  // deep copy via the identity op
    return Invoke("_copy", {*this}, {}).at(0);
  }

  /* imperative op by NAME — the registry is the source of truth */
  static std::vector<NDArray> Invoke(
      const std::string &op, const std::vector<NDArray> &inputs,
      const std::map<std::string, std::string> &params) {
    std::vector<NDArrayHandle> in;
    for (const auto &a : inputs) in.push_back(a.handle());
    std::vector<const char *> keys, vals;
    for (const auto &kv : params) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    int n_out = 0;
    NDArrayHandle *outs = nullptr;
    Check(MXImperativeInvoke(const_cast<char *>(op.c_str()),
                             static_cast<int>(in.size()), in.data(), &n_out,
                             &outs, static_cast<int>(keys.size()),
                             keys.data(), vals.data()),
          "ImperativeInvoke");
    std::vector<NDArray> result;
    for (int i = 0; i < n_out; ++i) result.emplace_back(outs[i]);
    return result;
  }

  /* in-place invoke: results are written INTO the caller's arrays
   * (the reference's pre-allocated-outputs ABI) */
  static void InvokeInto(const std::string &op,
                         const std::vector<NDArray> &inputs,
                         const std::map<std::string, std::string> &params,
                         const std::vector<NDArray> &outputs) {
    std::vector<NDArrayHandle> in, out;
    for (const auto &a : inputs) in.push_back(a.handle());
    for (const auto &a : outputs) out.push_back(a.handle());
    std::vector<const char *> keys, vals;
    for (const auto &kv : params) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    int n_out = static_cast<int>(out.size());
    NDArrayHandle *outp = out.data();
    Check(MXImperativeInvoke(const_cast<char *>(op.c_str()),
                             static_cast<int>(in.size()), in.data(), &n_out,
                             &outp, static_cast<int>(keys.size()),
                             keys.data(), vals.data()),
          "ImperativeInvoke(in-place)");
  }

  NDArray operator+(const NDArray &o) const {
    return Invoke("elemwise_add", {*this, o}, {}).at(0);
  }
  NDArray operator*(const NDArray &o) const {
    return Invoke("elemwise_mul", {*this, o}, {}).at(0);
  }

  static void Save(const std::string &fname,
                   const std::map<std::string, NDArray> &arrays) {
    std::vector<NDArrayHandle> hs;
    std::vector<const char *> names;
    for (const auto &kv : arrays) {
      names.push_back(kv.first.c_str());
      hs.push_back(kv.second.handle());
    }
    Check(MXNDArraySave(fname.c_str(), static_cast<mx_uint>(hs.size()),
                        hs.data(), names.data()),
          "NDArraySave");
  }
  static std::map<std::string, NDArray> Load(const std::string &fname) {
    mx_uint n = 0, nn = 0;
    NDArrayHandle *arrs = nullptr;
    const char **names = nullptr;
    Check(MXNDArrayLoad(fname.c_str(), &n, &arrs, &nn, &names),
          "NDArrayLoad");
    std::map<std::string, NDArray> out;
    for (mx_uint i = 0; i < n; ++i)
      out.emplace(nn == n ? names[i] : std::to_string(i), NDArray(arrs[i]));
    return out;
  }

 private:
  static std::shared_ptr<void> wrap(NDArrayHandle h) {
    return std::shared_ptr<void>(h, [](void *p) {
      if (p) MXNDArrayFree(p);
    });
  }
  std::shared_ptr<void> h_;
};

/* ---------------------------------------------------------- Symbol */
class Symbol {
 public:
  Symbol() = default;
  explicit Symbol(SymbolHandle h) : h_(wrap(h)) {}

  static Symbol Variable(const std::string &name) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateVariable(name.c_str(), &h), "CreateVariable");
    return Symbol(h);
  }
  static Symbol FromJSON(const std::string &json) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateFromJSON(json.c_str(), &h), "CreateFromJSON");
    return Symbol(h);
  }
  static Symbol Group(const std::vector<Symbol> &symbols) {
    std::vector<SymbolHandle> hs;
    for (const auto &s : symbols) hs.push_back(s.handle());
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateGroup(static_cast<mx_uint>(hs.size()), hs.data(),
                              &h),
          "CreateGroup");
    return Symbol(h);
  }

  SymbolHandle handle() const { return h_.get(); }

  std::string ToJSON() const {
    const char *json = nullptr;
    Check(MXSymbolSaveToJSON(h_.get(), &json), "SaveToJSON");
    return json;
  }
  std::vector<std::string> ListArguments() const {
    return StrList(&MXSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return StrList(&MXSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return StrList(&MXSymbolListAuxiliaryStates);
  }

  /* infer all argument/output shapes from the named known ones */
  void InferShape(
      const std::map<std::string, std::vector<mx_uint>> &known,
      std::vector<std::vector<mx_uint>> *arg_shapes,
      std::vector<std::vector<mx_uint>> *out_shapes,
      std::vector<std::vector<mx_uint>> *aux_shapes) const {
    std::vector<const char *> keys;
    std::vector<mx_uint> ind(1, 0), data;
    for (const auto &kv : known) {
      keys.push_back(kv.first.c_str());
      data.insert(data.end(), kv.second.begin(), kv.second.end());
      ind.push_back(static_cast<mx_uint>(data.size()));
    }
    mx_uint in_n, out_n, aux_n;
    const mx_uint *in_nd, *out_nd, *aux_nd;
    const mx_uint **in_d, **out_d, **aux_d;
    int complete = 0;
    Check(MXSymbolInferShape(h_.get(),
                             static_cast<mx_uint>(keys.size()), keys.data(),
                             ind.data(), data.data(), &in_n, &in_nd, &in_d,
                             &out_n, &out_nd, &out_d, &aux_n, &aux_nd,
                             &aux_d, &complete),
          "InferShape");
    auto unpack = [](mx_uint n, const mx_uint *nd, const mx_uint **d,
                     std::vector<std::vector<mx_uint>> *out) {
      if (!out) return;
      out->clear();
      for (mx_uint i = 0; i < n; ++i)
        out->emplace_back(d[i], d[i] + nd[i]);
    };
    unpack(in_n, in_nd, in_d, arg_shapes);
    unpack(out_n, out_nd, out_d, out_shapes);
    unpack(aux_n, aux_nd, aux_d, aux_shapes);
  }

 private:
  template <typename F>
  std::vector<std::string> StrList(F fn) const {
    mx_uint n = 0;
    const char **arr = nullptr;
    Check(fn(h_.get(), &n, &arr), "SymbolList");
    return std::vector<std::string>(arr, arr + n);
  }
  static std::shared_ptr<void> wrap(SymbolHandle h) {
    return std::shared_ptr<void>(h, [](void *p) {
      if (p) MXSymbolFree(p);
    });
  }
  std::shared_ptr<void> h_;
};

/* ------------------------------------------- the generic Operator.
 * The reference cpp-package's central idea: one class builds ANY
 * registered operator from (name, string params, inputs). */
class Operator {
 public:
  explicit Operator(const std::string &op_name) : name_(op_name) {}

  template <typename T>
  Operator &SetParam(const std::string &key, const T &value) {
    std::ostringstream os;
    os << value;
    params_[key] = os.str();
    return *this;
  }
  /* Named input: composed onto the op's declared slot of that name
   * (order of SetInput calls does not matter). */
  Operator &SetInput(const std::string &name, const Symbol &sym) {
    input_names_.push_back(name);
    inputs_.push_back(sym);
    return *this;
  }
  /* Positional input (reference operator() chaining). Mixing unnamed
   * and named inputs falls back to positional order for all. */
  Operator &operator()(const Symbol &sym) { return SetInput("", sym); }

  Symbol CreateSymbol(const std::string &instance_name = "") {
    std::vector<const char *> keys, vals;
    for (const auto &kv : params_) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateAtomicSymbol(
              const_cast<char *>(name_.c_str()),
              static_cast<mx_uint>(keys.size()), keys.data(), vals.data(),
              &h),
          "CreateAtomicSymbol");
    Symbol owned(h);  // RAII before Compose so a failure cannot leak h
    std::vector<SymbolHandle> args;
    for (const auto &s : inputs_) args.push_back(s.handle());
    bool named = !input_names_.empty();
    for (const auto &n : input_names_)
      if (n.empty()) named = false;
    std::vector<const char *> in_keys;
    for (const auto &n : input_names_) in_keys.push_back(n.c_str());
    Check(MXSymbolCompose(h, instance_name.c_str(),
                          static_cast<mx_uint>(args.size()),
                          named ? in_keys.data() : nullptr, args.data()),
          "SymbolCompose");
    return owned;
  }

 private:
  std::string name_;
  std::map<std::string, std::string> params_;
  std::vector<std::string> input_names_;
  std::vector<Symbol> inputs_;
};

/* ----------------------------- introspection (binding-generator view) */
struct OperatorInfo {
  std::string name, description, key_var_num_args, return_type;
  std::vector<std::string> arg_names, arg_types, arg_descriptions;
};

inline std::vector<std::string> ListOperators() {
  mx_uint n = 0;
  const char **arr = nullptr;
  Check(MXListAllOpNames(&n, &arr), "ListAllOpNames");
  return std::vector<std::string>(arr, arr + n);
}

inline OperatorInfo GetOperatorInfo(const std::string &op_name) {
  AtomicSymbolCreator creator =
      const_cast<char *>(op_name.c_str());  // name-addressing convention
  const char *name, *desc, *keyvar, *rett;
  mx_uint n_args;
  const char **anames, **atypes, **adescs;
  Check(MXSymbolGetAtomicSymbolInfo(creator, &name, &desc, &n_args,
                                    &anames, &atypes, &adescs, &keyvar,
                                    &rett),
        "GetAtomicSymbolInfo");
  OperatorInfo info;
  info.name = name;
  info.description = desc;
  info.key_var_num_args = keyvar;
  info.return_type = rett;
  for (mx_uint i = 0; i < n_args; ++i) {
    info.arg_names.emplace_back(anames[i]);
    info.arg_types.emplace_back(atypes[i]);
    info.arg_descriptions.emplace_back(adescs[i]);
  }
  return info;
}

/* -------------------------------------------------------- Executor */
class Executor {
 public:
  /* SimpleBind: allocate-and-bind with per-name grad requests
   * ("null"/"write"/"add"); params not in `grad_reqs` default to the
   * dict semantics (missing -> null). */
  Executor(const Symbol &sym, Context ctx,
           const std::map<std::string, std::vector<mx_uint>> &arg_shapes,
           const std::map<std::string, std::string> &grad_reqs)
      : sym_(sym) {
    std::vector<const char *> req_names, req_types;
    for (const auto &kv : grad_reqs) {
      req_names.push_back(kv.first.c_str());
      req_types.push_back(kv.second.c_str());
    }
    std::vector<const char *> shape_names;
    std::vector<mx_uint> shape_data, shape_idx(1, 0);
    for (const auto &kv : arg_shapes) {
      shape_names.push_back(kv.first.c_str());
      shape_data.insert(shape_data.end(), kv.second.begin(),
                        kv.second.end());
      shape_idx.push_back(static_cast<mx_uint>(shape_data.size()));
    }
    int shared_len = -1;
    mx_uint n_in = 0, n_aux = 0;
    NDArrayHandle *in = nullptr, *grads = nullptr, *aux = nullptr;
    Check(MXExecutorSimpleBind(
              sym.handle(), ctx.dev_type, ctx.dev_id, 0, nullptr, nullptr,
              nullptr, static_cast<mx_uint>(req_names.size()),
              req_names.data(), req_types.data(),
              static_cast<mx_uint>(shape_names.size()), shape_names.data(),
              shape_data.data(), shape_idx.data(), 0, nullptr, nullptr, 0,
              nullptr, &shared_len, nullptr, nullptr, nullptr, nullptr,
              &n_in, &in, &grads, &n_aux, &aux, nullptr, &h_),
          "SimpleBind");
    try {
      auto arg_names = sym.ListArguments();
      for (mx_uint i = 0; i < n_in; ++i) {
        arg_dict_.emplace(arg_names[i], NDArray(in[i]));
        if (grads[i]) grad_dict_.emplace(arg_names[i], NDArray(grads[i]));
      }
      auto aux_names = sym.ListAuxiliaryStates();
      for (mx_uint i = 0; i < n_aux; ++i)
        aux_dict_.emplace(aux_names[i], NDArray(aux[i]));
    } catch (...) {
      // a throwing ctor never runs ~Executor — free the handle here
      MXExecutorFree(h_);
      throw;
    }
  }
  ~Executor() {
    if (h_) MXExecutorFree(h_);
  }
  Executor(const Executor &) = delete;
  Executor &operator=(const Executor &) = delete;

  std::map<std::string, NDArray> &arg_dict() { return arg_dict_; }
  std::map<std::string, NDArray> &grad_dict() { return grad_dict_; }
  std::map<std::string, NDArray> &aux_dict() { return aux_dict_; }

  void Forward(bool is_train) {
    Check(MXExecutorForward(h_, is_train ? 1 : 0), "Forward");
  }
  void Backward(const std::vector<NDArray> &head_grads = {}) {
    std::vector<NDArrayHandle> hs;
    for (const auto &g : head_grads) hs.push_back(g.handle());
    Check(MXExecutorBackward(h_, static_cast<mx_uint>(hs.size()),
                             hs.data()),
          "Backward");
  }
  std::vector<NDArray> Outputs() const {
    mx_uint n = 0;
    NDArrayHandle *outs = nullptr;
    Check(MXExecutorOutputs(h_, &n, &outs), "Outputs");
    std::vector<NDArray> result;
    for (mx_uint i = 0; i < n; ++i) result.emplace_back(outs[i]);
    return result;
  }

 private:
  Symbol sym_;
  ExecutorHandle h_ = nullptr;
  std::map<std::string, NDArray> arg_dict_, grad_dict_, aux_dict_;
};

/* ------------------------------------------------------- Optimizer.
 * SGD over the registry's fused update op — each update is one
 * in-place imperative invoke (pre-allocated output = the weight). */
class SGDOptimizer {
 public:
  explicit SGDOptimizer(float lr, float wd = 0.0f) : lr_(lr), wd_(wd) {}
  void Update(NDArray *weight, const NDArray &grad) {
    std::map<std::string, std::string> p{
        {"lr", std::to_string(lr_)}, {"wd", std::to_string(wd_)}};
    // in-place: the result lands in the weight's own (bound) buffer,
    // so an executor holding this array sees the update
    NDArray::InvokeInto("sgd_update", {*weight, grad}, p, {*weight});
  }

 private:
  float lr_, wd_;
};

/* --------------------------------------------------------- KVStore */
class KVStore {
 public:
  explicit KVStore(const std::string &type = "local") {
    Check(MXKVStoreCreate(type.c_str(), &h_), "KVStoreCreate");
  }
  ~KVStore() {
    if (h_) MXKVStoreFree(h_);
  }
  KVStore(const KVStore &) = delete;
  KVStore &operator=(const KVStore &) = delete;

  void Init(int key, const NDArray &val) {
    NDArrayHandle h = val.handle();
    Check(MXKVStoreInit(h_, 1, &key, &h), "KVStoreInit");
  }
  void Push(int key, const NDArray &val, int priority = 0) {
    NDArrayHandle h = val.handle();
    Check(MXKVStorePush(h_, 1, &key, &h, priority), "KVStorePush");
  }
  void Pull(int key, NDArray *out, int priority = 0) {
    NDArrayHandle h = out->handle();
    Check(MXKVStorePull(h_, 1, &key, &h, priority), "KVStorePull");
  }
  int Rank() const {
    int r = 0;
    Check(MXKVStoreGetRank(h_, &r), "GetRank");
    return r;
  }
  int NumWorkers() const {
    int n = 0;
    Check(MXKVStoreGetGroupSize(h_, &n), "GetGroupSize");
    return n;
  }

 private:
  KVStoreHandle h_ = nullptr;
};

/* -------------------------------------------------------- CachedOp */
class CachedOp {
 public:
  explicit CachedOp(const Symbol &sym) {
    Check(MXCreateCachedOp(sym.handle(), &h_), "CreateCachedOp");
  }
  ~CachedOp() {
    if (h_) MXFreeCachedOp(h_);
  }
  CachedOp(const CachedOp &) = delete;
  CachedOp &operator=(const CachedOp &) = delete;

  /* inputs in list_arguments order; per-signature executor reuse */
  std::vector<NDArray> operator()(const std::vector<NDArray> &inputs) {
    std::vector<NDArrayHandle> in;
    for (const auto &a : inputs) in.push_back(a.handle());
    int n_out = 0;
    NDArrayHandle *outs = nullptr;
    Check(MXInvokeCachedOp(h_, static_cast<int>(in.size()), in.data(),
                           &n_out, &outs),
          "InvokeCachedOp");
    std::vector<NDArray> result;
    for (int i = 0; i < n_out; ++i) result.emplace_back(outs[i]);
    return result;
  }

 private:
  CachedOpHandle h_ = nullptr;
};

/* -------------------------------------------------------- Autograd.
 * Imperative tape over NDArray::Invoke calls: mark variables, run ops
 * inside a Recording scope, Backward fills the marked grad arrays. */
namespace autograd {

class Recording {  // RAII train-mode toggle
 public:
  Recording() { Check(MXAutogradSetIsTraining(1, &prev_), "SetIsTraining"); }
  ~Recording() {
    int unused = 0;
    MXAutogradSetIsTraining(prev_, &unused);
  }
  Recording(const Recording &) = delete;
  Recording &operator=(const Recording &) = delete;

 private:
  int prev_ = 0;
};

/* grad req: 0 null, 1 write, 3 add */
inline void MarkVariables(const std::vector<NDArray> &vars,
                          const std::vector<NDArray> &grads,
                          mx_uint req = 1) {
  if (vars.size() != grads.size())
    throw std::runtime_error(
        "autograd::MarkVariables: vars/grads size mismatch");
  std::vector<NDArrayHandle> vh, gh;
  for (const auto &v : vars) vh.push_back(v.handle());
  for (const auto &g : grads) gh.push_back(g.handle());
  std::vector<mx_uint> reqs(vars.size(), req);
  Check(MXAutogradMarkVariables(static_cast<mx_uint>(vh.size()), vh.data(),
                                reqs.data(), gh.data()),
        "MarkVariables");
}

/* Default-NDArray (is_none) or missing trailing entries in head_grads
 * mean a ones-gradient for that output (the C ABI's NULL convention) */
inline void Backward(const std::vector<NDArray> &outputs,
                     const std::vector<NDArray> &head_grads = {},
                     bool retain_graph = false) {
  if (head_grads.size() > outputs.size())
    throw std::runtime_error(
        "autograd::Backward: more head_grads than outputs");
  std::vector<NDArrayHandle> oh, gh;
  for (const auto &o : outputs) oh.push_back(o.handle());
  for (const auto &g : head_grads)
    gh.push_back(g.is_none() ? nullptr : g.handle());
  gh.resize(oh.size(), nullptr);  // pad: ones-gradient for the rest
  Check(MXAutogradBackward(static_cast<mx_uint>(oh.size()), oh.data(),
                           head_grads.empty() ? nullptr : gh.data(),
                           retain_graph ? 1 : 0),
        "AutogradBackward");
}

}  // namespace autograd

/* -------------------------------------------------------- DataIter */
class DataIter {
 public:
  /* Create a registered iterator by name (MNISTIter, CSVIter,
   * ImageRecordIter, ImageDetRecordIter); param values are python
   * literals as strings, e.g. {"data_shape", "(3,32,32)"}. */
  DataIter(const std::string &name,
           const std::map<std::string, std::string> &params) {
    std::vector<const char *> keys, vals;
    for (const auto &kv : params) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    Check(MXDataIterCreateIter(const_cast<char *>(name.c_str()),
                               static_cast<mx_uint>(keys.size()),
                               keys.data(), vals.data(), &h_),
          "DataIterCreateIter");
  }
  ~DataIter() {
    if (h_) MXDataIterFree(h_);
  }
  DataIter(const DataIter &) = delete;
  DataIter &operator=(const DataIter &) = delete;

  bool Next() {
    int has = 0;
    Check(MXDataIterNext(h_, &has), "DataIterNext");
    return has != 0;
  }
  void Reset() { Check(MXDataIterBeforeFirst(h_), "DataIterBeforeFirst"); }
  NDArray Data() const {
    NDArrayHandle out = nullptr;
    Check(MXDataIterGetData(h_, &out), "DataIterGetData");
    return NDArray(out);
  }
  NDArray Label() const {
    NDArrayHandle out = nullptr;
    Check(MXDataIterGetLabel(h_, &out), "DataIterGetLabel");
    return NDArray(out);
  }
  int PadNum() const {
    int pad = 0;
    Check(MXDataIterGetPadNum(h_, &pad), "DataIterGetPadNum");
    return pad;
  }

 private:
  DataIterHandle h_ = nullptr;
};

/* -------------------------------------------------------- RecordIO */
class RecordWriter {
 public:
  explicit RecordWriter(const std::string &uri) {
    Check(MXRecordIOWriterCreate(uri.c_str(), &h_), "RecordIOWriterCreate");
  }
  ~RecordWriter() {
    if (h_) MXRecordIOWriterFree(h_);
  }
  RecordWriter(const RecordWriter &) = delete;
  RecordWriter &operator=(const RecordWriter &) = delete;

  void Write(const std::string &record) {
    Check(MXRecordIOWriterWriteRecord(h_, record.data(), record.size()),
          "RecordIOWriterWriteRecord");
  }
  size_t Tell() const {
    size_t pos = 0;
    Check(MXRecordIOWriterTell(h_, &pos), "RecordIOWriterTell");
    return pos;
  }

 private:
  RecordIOHandle h_ = nullptr;
};

class RecordReader {
 public:
  explicit RecordReader(const std::string &uri) {
    Check(MXRecordIOReaderCreate(uri.c_str(), &h_), "RecordIOReaderCreate");
  }
  ~RecordReader() {
    if (h_) MXRecordIOReaderFree(h_);
  }
  RecordReader(const RecordReader &) = delete;
  RecordReader &operator=(const RecordReader &) = delete;

  /* false at EOF; otherwise *record holds the payload */
  bool Read(std::string *record) {
    const char *buf = nullptr;
    size_t size = 0;
    Check(MXRecordIOReaderReadRecord(h_, &buf, &size),
          "RecordIOReaderReadRecord");
    if (!buf) return false;
    record->assign(buf, size);
    return true;
  }
  void Seek(size_t pos) {
    Check(MXRecordIOReaderSeek(h_, pos), "RecordIOReaderSeek");
  }

 private:
  RecordIOHandle h_ = nullptr;
};

}  // namespace mxtpu

#endif  // MXNET_TPU_CPP_HPP_
