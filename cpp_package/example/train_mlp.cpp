/* Train an MLP classifier from C++ — the cpp-package workflow end to
 * end: generic Operator symbol building, SimpleBind, forward/backward,
 * fused-op SGD updates, KVStore round-trip, and op introspection (what
 * a binding generator reads).  Mirrors the reference
 * cpp-package/example/mlp.cpp shape on synthetic separable data.
 *
 *   g++ -std=c++17 train_mlp.cpp -I ../../include -I ../include \
 *       -L <libdir> -lmxnet_tpu -Wl,-rpath,<libdir> -o train_mlp
 */
#include <mxnet_tpu.hpp>

#include <cmath>
#include <cstdio>
#include <random>
#include <unistd.h>
#include <vector>

using namespace mxtpu;

int main() {
  const int kBatch = 64, kDim = 10, kClasses = 3, kHidden = 32;
  const int kSteps = 60;

  /* ---- introspection: enumerate ops, read one signature ---- */
  auto ops = ListOperators();
  auto fc_info = GetOperatorInfo("FullyConnected");
  std::printf("ops: %zu, FullyConnected params: %zu (%s...)\n", ops.size(),
              fc_info.arg_names.size(),
              fc_info.arg_names.empty() ? "-" : fc_info.arg_names[0].c_str());

  /* ---- model: the reference cpp-package Operator idiom ---- */
  Symbol data = Symbol::Variable("data");
  Symbol label = Symbol::Variable("softmax_label");
  Symbol fc1 = Operator("FullyConnected")
                   .SetParam("num_hidden", kHidden)
                   .SetInput("data", data)
                   .CreateSymbol("fc1");
  Symbol act = Operator("Activation")
                   .SetParam("act_type", "relu")
                   .SetInput("data", fc1)
                   .CreateSymbol("relu1");
  Symbol fc2 = Operator("FullyConnected")
                   .SetParam("num_hidden", kClasses)
                   .SetInput("data", act)
                   .CreateSymbol("fc2");
  /* named inputs compose onto the op's declared slots regardless of
   * call order — label first on purpose */
  Symbol net = Operator("SoftmaxOutput")
                   .SetInput("label", label)
                   .SetInput("data", fc2)
                   .CreateSymbol("softmax");

  /* ---- synthetic separable clusters ---- */
  std::mt19937 rng(7);
  std::normal_distribution<float> gauss(0.f, 1.f);
  std::vector<float> centers(kClasses * kDim);
  for (auto &c : centers) c = 2.5f * gauss(rng);
  std::vector<float> xs(kBatch * kDim), ys(kBatch);
  auto resample = [&]() {
    for (int i = 0; i < kBatch; ++i) {
      int cls = static_cast<int>(rng() % kClasses);
      ys[i] = static_cast<float>(cls);
      for (int d = 0; d < kDim; ++d)
        xs[i * kDim + d] = centers[cls * kDim + d] + gauss(rng);
    }
  };

  /* ---- SimpleBind: params train, inputs stay null ---- */
  Executor exe(net, Context::cpu(),
               {{"data", {kBatch, kDim}}, {"softmax_label", {kBatch}}},
               {{"fc1_weight", "write"},
                {"fc1_bias", "write"},
                {"fc2_weight", "write"},
                {"fc2_bias", "write"}});

  /* Xavier-ish init from the host */
  for (auto &kv : exe.arg_dict()) {
    if (kv.first == "data" || kv.first == "softmax_label") continue;
    size_t n = kv.second.Size();
    std::vector<float> w(n);
    for (auto &v : w) v = 0.2f * gauss(rng);
    kv.second.SyncCopyFromCPU(w);
  }

  SGDOptimizer opt(0.1f, 1e-4f);
  float first_loss = -1.f, loss = 0.f;
  for (int step = 0; step < kSteps; ++step) {
    resample();
    exe.arg_dict()["data"].SyncCopyFromCPU(xs);
    exe.arg_dict()["softmax_label"].SyncCopyFromCPU(ys);
    exe.Forward(true);
    auto probs = exe.Outputs()[0].SyncCopyToCPU();
    loss = 0.f;
    for (int i = 0; i < kBatch; ++i)
      loss += -std::log(
          std::max(probs[i * kClasses + static_cast<int>(ys[i])], 1e-8f));
    loss /= kBatch;
    if (step == 0) first_loss = loss;
    exe.Backward();
    for (auto &kv : exe.grad_dict())  // in-place update of bound buffers
      opt.Update(&exe.arg_dict()[kv.first], kv.second);
  }

  /* final training accuracy */
  exe.Forward(false);
  auto probs = exe.Outputs()[0].SyncCopyToCPU();
  int correct = 0;
  for (int i = 0; i < kBatch; ++i) {
    int best = 0;
    for (int c = 1; c < kClasses; ++c)
      if (probs[i * kClasses + c] > probs[i * kClasses + best]) best = c;
    correct += (best == static_cast<int>(ys[i]));
  }
  std::printf("loss %.3f -> %.3f, accuracy %.3f\n", first_loss, loss,
              correct / static_cast<float>(kBatch));

  /* ---- KVStore round-trip ---- */
  KVStore kv("local");
  NDArray v(std::vector<float>{1, 2, 3}, {3});
  kv.Init(0, v);
  kv.Push(0, v, 0);
  NDArray out({3});
  kv.Pull(0, &out, 0);
  auto pulled = out.SyncCopyToCPU();
  std::printf("kvstore: rank %d/%d pull [%g %g %g]\n", kv.Rank(),
              kv.NumWorkers(), pulled[0], pulled[1], pulled[2]);

  /* ---- RecordIO round-trip ---- */
  bool rec_ok = false;
  {
    char uri[64];
    std::snprintf(uri, sizeof(uri), "/tmp/cpp_example.%d.rec",
                  (int)getpid());  // unique per process; removed below
    const std::string binary("binary\0data", 11);
    {
      RecordWriter w(uri);
      w.Write("first record");
      w.Write(binary);
    }
    RecordReader r(uri);
    std::string rec1, rec2, rec3;
    rec_ok = r.Read(&rec1) && r.Read(&rec2) && !r.Read(&rec3) &&
             rec1 == "first record" && rec2 == binary;
    std::printf("recordio: round-trip %s\n", rec_ok ? "ok" : "FAILED");
    std::remove(uri);
  }

  /* ---- CachedOp replay + imperative autograd ---- */
  bool extra_ok = false;
  {
    CachedOp cop(fc1);  // fc1 symbol: data @ W.T + b
    std::vector<NDArray> cin;
    cin.emplace_back(std::vector<float>(kBatch * kDim, 1.f),
                     std::vector<mx_uint>{kBatch, kDim});
    cin.push_back(exe.arg_dict()["fc1_weight"].Copy());
    cin.push_back(exe.arg_dict()["fc1_bias"].Copy());
    auto y1 = cop(cin).at(0).SyncCopyToCPU();
    /* NEW input values through the same signature: the cached executor
     * must recompute, not replay stale outputs */
    cin[0] = NDArray(std::vector<float>(kBatch * kDim, 2.f),
                     {kBatch, kDim});
    auto y2 = cop(cin).at(0).SyncCopyToCPU();
    /* a SECOND shape signature exercises the per-signature cache */
    std::vector<NDArray> cin2{NDArray(std::vector<float>(3 * kDim, 1.f),
                                      {3, kDim}),
                              cin[1], cin[2]};
    auto y3 = cop(cin2).at(0);
    bool cached_same = y1 != y2 && y3.Shape()[0] == 3 &&
                       std::abs(2 * y1[0] - y2[0] -
                                exe.arg_dict()["fc1_bias"]
                                    .SyncCopyToCPU()[0]) < 1e-3f;

    // autograd: d/dx sum(x*x) = 2x, via the recorded imperative tape
    NDArray ax(std::vector<float>{1, 2, 3}, {3});
    NDArray agrad({3});
    autograd::MarkVariables({ax}, {agrad});
    std::vector<NDArray> ys;
    {
      autograd::Recording rec;
      ys = NDArray::Invoke("elemwise_mul", {ax, ax}, {});
    }
    autograd::Backward(ys);
    auto g = agrad.SyncCopyToCPU();
    extra_ok = cached_same && g[0] == 2.f && g[1] == 4.f && g[2] == 6.f;
    std::printf("cachedop+autograd: %s (dx = [%g %g %g])\n",
                extra_ok ? "ok" : "FAILED", g[0], g[1], g[2]);
  }

  bool ok = loss < 0.5f * first_loss && correct >= kBatch * 0.9 &&
            pulled[2] == 3.0f && rec_ok && extra_ok;
  std::printf(ok ? "CPP_OK\n" : "CPP_FAIL\n");
  return ok ? 0 : 1;
}
